"""Concurrent query serving tier-1 tests (spark_rapids_tpu/serving):

- plan signatures: normalized-structure sharing across literal values,
  exact identity, default-deny on unsignable state, file fingerprints;
- the two cross-query caches: exact-repeat plan-cache hits with ZERO new
  traces (the ISSUE 15 acceptance assertion, via the stage compiler's
  counters), literal-promoted structure sharing, busy-bypass leasing,
  result-cache spill round trip and invalidation on input-file change;
- admission control: a starved pool BLOCKS submissions (never OOMs),
  sheds them with AdmissionTimeout past the queue timeout, and surfaces
  waits through the arbiter's serving view;
- concurrent bit-identity: N queries racing == serial results;
- the online AutoTuner loop: accepted conf deltas apply to the NEXT
  admitted query (conf-digest re-plan), resize the live semaphore, and
  leave an autotuneApplied trail;
- the PR 15 satellites: CTE-cache execution epochs, the deferred-concat
  padding guard, and first-batch-sampled build-side swap.
"""

import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.aux import events as EV
from spark_rapids_tpu.exec import stage_compiler as SC
from spark_rapids_tpu.serving import AdmissionTimeout, QueryServer
from spark_rapids_tpu.serving.caches import PlanCache, ResultCache
from spark_rapids_tpu.serving.server import AdmissionController
from spark_rapids_tpu.serving.signature import (conf_digest,
                                                plan_fingerprints,
                                                plan_signature)

from tests.asserts import tpu_session


def _write_store(tmp_path, n=2000, seed=7):
    rng = np.random.default_rng(seed)
    t = pa.table({
        "k": rng.integers(0, 9, n).astype(np.int64),
        "g": rng.integers(0, 4, n).astype(np.int64),
        "v": rng.standard_normal(n),
    })
    path = str(tmp_path / "serve_t.parquet")
    pq.write_table(t, path)
    return path


def _serving_session(tmp_path, extra=None):
    s = tpu_session(extra)
    path = _write_store(tmp_path)
    s.create_or_replace_temp_view("t", s.read.parquet(path))
    return s, path


class _Server:
    """Context-managed QueryServer (workers must stop even on failure)."""

    def __init__(self, session, **conf):
        for k, v in conf.items():
            session = session.set_conf(k, v)
        self.srv = QueryServer(session=session)

    def __enter__(self):
        return self.srv

    def __exit__(self, *exc):
        self.srv.stop()
        return False


Q_AGG = ("SELECT k, SUM(v) AS s, COUNT(*) AS c FROM t "
         "WHERE v > 0 GROUP BY k ORDER BY k")
Q_FILTER = "SELECT k, g, v FROM t WHERE v > 1.5 ORDER BY v DESC, k, g"
Q_GROUP2 = ("SELECT g, MIN(v) AS lo, MAX(v) AS hi FROM t "
            "GROUP BY g ORDER BY g")
MIXED = [Q_AGG, Q_FILTER, Q_GROUP2]


# ---------------------------------------------------------------------------
# signatures
# ---------------------------------------------------------------------------

def test_signature_structure_shared_across_literals(tmp_path):
    s, _ = _serving_session(tmp_path)
    a = plan_signature(s.sql(Q_AGG)._plan)
    b = plan_signature(s.sql(Q_AGG)._plan)
    c = plan_signature(s.sql(Q_AGG.replace("v > 0", "v > 2"))._plan)
    d = plan_signature(s.sql(Q_FILTER)._plan)
    assert a is not None and a.norm == b.norm
    assert a.lit_values == b.lit_values and a.exact == b.exact
    # same structure, different literal vector -> same entry, new variant
    assert c.norm == a.norm
    assert c.lit_values != a.lit_values and c.exact != a.exact
    # different structure
    assert d.norm != a.norm


def test_signature_stable_across_reparse_with_subqueries(tmp_path):
    # regression: the analyzer's subquery marker counter (_exists<N> /
    # _sq<N> internal column names) was process-global, so two parses of
    # the SAME text produced different structures and identical queries
    # missed the plan cache.  Markers now number per-parse.
    s, _ = _serving_session(tmp_path)
    q = ("SELECT k, v FROM t WHERE EXISTS "
         "(SELECT 1 FROM t t2 WHERE t2.k = t.k AND t2.v > 1) "
         "AND v < (SELECT MAX(v) FROM t) ORDER BY k, v")
    a = plan_signature(s.sql(q)._plan)
    b = plan_signature(s.sql(q)._plan)
    assert a is not None and a.norm == b.norm and a.exact == b.exact


def test_signature_default_denies_unsignable_state(tmp_path):
    s, _ = _serving_session(tmp_path)
    plan = s.sql(Q_AGG)._plan
    assert plan_signature(plan) is not None
    # a node carrying a callable (python UDFs, pandas fns) makes the
    # whole plan unsigned: wrongly merging two UDF plans is never ok
    plan.children[0].mystery_fn = lambda row: row
    try:
        assert plan_signature(plan) is None
    finally:
        del plan.children[0].mystery_fn


def test_fingerprints_track_file_change_and_deletion(tmp_path):
    s, path = _serving_session(tmp_path)
    plan = s.sql(Q_AGG)._plan
    fp0 = plan_fingerprints(plan)
    assert any(f[0] == path and f[2] > 0 for f in fp0)
    t = pq.read_table(path)
    time.sleep(0.02)
    pq.write_table(t.slice(0, 100), path)
    fp1 = plan_fingerprints(plan)
    assert fp1 != fp0
    import os
    os.remove(path)
    fp2 = plan_fingerprints(plan)
    assert any(f[0] == path and f[2] == -1 for f in fp2)


def test_conf_digest_ignores_serving_keys(tmp_path):
    s, _ = _serving_session(tmp_path)
    d0 = conf_digest(s.conf)
    d1 = conf_digest(
        s.conf.set("spark.rapids.serving.maxConcurrentQueries", "2"))
    assert d0 == d1
    d2 = conf_digest(s.conf.set("spark.rapids.sql.batchSizeBytes", "1m"))
    assert d2 != d0


# ---------------------------------------------------------------------------
# cache units
# ---------------------------------------------------------------------------

class _FakeSig:
    def __init__(self, norm, lits=()):
        self.norm = norm
        self.lit_values = tuple(lits)


def test_plan_cache_lease_busy_bypass_and_eviction():
    pc = PlanCache(max_plans=2)
    fp = (("f", 1.0, 10),)
    s1 = _FakeSig("n1", ("1",))
    lease = pc.insert("cd", s1, fp, plan="P1")
    # the inserted variant is LEASED: a concurrent identical query must
    # bypass instead of racing the same exec instances
    assert pc.lookup("cd", s1, fp) is None
    assert pc.stats["busy_bypass"] == 1
    lease.release()
    hit = pc.lookup("cd", s1, fp)
    assert hit is not None and hit.plan == "P1"
    hit.release()
    # same structure / new literal vector: norm_hit, caller plans fresh
    s2 = _FakeSig("n1", ("2",))
    assert pc.lookup("cd", s2, fp) is None
    assert pc.stats["norm_hits"] == 1
    pc.insert("cd", s2, fp, plan="P2").release()
    # LRU bound counts variants; a third pushes the oldest unleased out
    pc.insert("cd", _FakeSig("n3"), fp, plan="P3").release()
    assert pc.stats["evictions"] >= 1
    # stale fingerprints drop the whole structure entry
    s_live = next(iter(pc._entries))
    lv = next(iter(pc._entries[s_live]))
    pc._entries[s_live][lv].fingerprints = (("f", 2.0, 11),)
    assert pc.lookup(s_live[0], _FakeSig(s_live[1], lv), fp) is None
    assert pc.stats["invalidations"] >= 1


def test_plan_cache_byte_bound_evicts_and_accounts():
    """spark.rapids.serving.planCache.maxBytes: retention is bounded by
    estimated plan bytes alongside the variant count — whichever trips
    first evicts — and the byte gauge tracks every mutation path."""
    fp = (("f", 1.0, 10),)
    # find the per-variant estimate so the bound can be set to ~2 plans
    probe = PlanCache(max_plans=8)
    probe.insert("cd", _FakeSig("n0"), fp, plan="P0").release()
    per = probe.total_bytes
    assert per > 0
    pc = PlanCache(max_plans=8, max_bytes=int(per * 2.5))
    for i in range(4):
        pc.insert("cd", _FakeSig(f"n{i}"), fp, plan=f"P{i}").release()
    # count bound (8) never tripped; the byte bound held retention at 2
    assert pc._variant_count() == 2
    assert pc.stats["evictions"] == 2
    assert pc.total_bytes == pc._variant_count() * per
    assert 0 < pc.total_bytes <= pc.max_bytes
    # discard of a leased variant returns its bytes
    lease = pc.lookup("cd", _FakeSig("n3"), fp)
    assert lease is not None
    pc.discard(lease)
    assert pc.total_bytes == pc._variant_count() * per
    pc.clear()
    assert pc.total_bytes == 0
    # 0 = unbounded: the seed behavior is unchanged
    pc2 = PlanCache(max_plans=8, max_bytes=0)
    for i in range(6):
        pc2.insert("cd", _FakeSig(f"m{i}"), fp, plan=f"P{i}").release()
    assert pc2._variant_count() == 6 and pc2.stats["evictions"] == 0


def test_result_cache_spill_round_trip():
    from spark_rapids_tpu.columnar.batch import batch_from_pydict
    b1 = batch_from_pydict({"x": np.arange(512, dtype=np.int64),
                            "s": [f"r{i}" for i in range(512)]})
    b2 = batch_from_pydict({"x": np.arange(7, dtype=np.int64)})
    rc = ResultCache(max_bytes=b1.nbytes() + 16, spill=True)
    fp = ()
    assert rc.put("k1", fp, b1)
    assert rc.put("k2", fp, b2)      # pressure: k1 spills to arrow tier
    assert rc.stats["spills"] == 1 and rc.disk_bytes > 0
    back = rc.lookup("k1", fp)
    assert back is not None and rc.stats["unspills"] == 1
    assert back.to_pydict() == b1.to_pydict()
    # fingerprint mismatch invalidates instead of serving stale
    assert rc.lookup("k2", (("f", 1.0, 1),)) is None
    assert rc.stats["invalidations"] == 1
    rc.clear()
    assert rc.mem_bytes == 0 and rc.disk_bytes == 0


def test_result_cache_spill_rides_the_catalog_codec_frame():
    # spill files are shuffle-serializer codec frames honoring
    # spark.rapids.memory.spill.codec, not raw arrow IPC
    from spark_rapids_tpu.columnar.batch import batch_from_pydict
    from spark_rapids_tpu.memory import catalog as CAT
    from spark_rapids_tpu.shuffle.serializer import deserialize_batch
    b1 = batch_from_pydict({"x": np.arange(512, dtype=np.int64),
                            "s": [f"r{i}" for i in range(512)]})
    b2 = batch_from_pydict({"x": np.arange(7, dtype=np.int64)})
    old_codec = CAT.SPILL_CODEC
    CAT.SPILL_CODEC = "zlib"
    try:
        rc = ResultCache(max_bytes=b1.nbytes() + 16, spill=True)
        assert rc.put("k1", (), b1)
        assert rc.put("k2", (), b2)     # pressure: k1 spills
        assert rc.stats["spills"] == 1
        e = rc._entries["k1"]
        with open(e.spill_path, "rb") as f:
            frame = f.read()
        assert frame[0] == 2            # zlib frame tag
        assert deserialize_batch(frame).to_pydict() == b1.to_pydict()
        # and the cache's own unspill path round-trips the frame
        back = rc.lookup("k1", ())
        assert back is not None and back.to_pydict() == b1.to_pydict()
        rc.clear()
    finally:
        CAT.SPILL_CODEC = old_codec


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def test_admission_starved_pool_blocks_then_admits_on_release():
    from spark_rapids_tpu.memory.arbiter import get_arbiter
    ac = AdmissionController(max_concurrent=4, reserve_bytes=600,
                            timeout_ms=30_000, backoff_ms=5)
    ac._pool_limit = lambda: 1000
    assert ac.admit(1) == 600        # first admits even when oversized
    admitted = threading.Event()

    def second():
        ac.admit(2)
        admitted.set()

    t = threading.Thread(target=second, daemon=True)
    t.start()
    # the starved pool BLOCKS the second submission (never OOMs)
    assert not admitted.wait(0.25)
    view = get_arbiter().serving_view()
    assert view[2]["state"] == "blocked_on_admission"
    assert "serving query 2" in get_arbiter().dump()
    ac.release(1)
    assert admitted.wait(5.0)
    t.join(5.0)
    ac.release(2)
    assert not get_arbiter().serving_view()


def test_admission_timeout_sheds_load():
    ac = AdmissionController(max_concurrent=4, reserve_bytes=600,
                            timeout_ms=120, backoff_ms=5)
    ac._pool_limit = lambda: 1000
    ac.admit(1)
    with pytest.raises(AdmissionTimeout):
        ac.admit(2)
    assert ac.stats["timeouts"] == 1
    ac.release(1)
    # queue stats survived the shed
    assert ac.stats["admitted"] == 1 and ac.stats["queued"] == 1


def test_serving_starved_pool_end_to_end(tmp_path):
    s, _ = _serving_session(tmp_path)
    # result cache OFF: identical repeats resolve pre-admission from the
    # cache and would never touch the starved pool this test exercises
    with _Server(s, **{"spark.rapids.serving.resultCache.maxBytes": "0"}
                 ) as srv:
        # serialize admissions through a tiny synthetic pool: every query
        # still completes (blocked, not shed, not OOMed)
        srv.admission._pool_limit = lambda: 1000
        srv.admission._reserve_bytes = 600
        expected = srv.execute(Q_AGG)
        subs = [srv.submit(Q_AGG) for _ in range(4)]
        assert all(sub.result(120) == expected for sub in subs)
        st = srv.stats()["admission"]
        assert st["timeouts"] == 0 and st["admitted"] == 5
        assert st["queued"] >= 1    # at least one wait was surfaced


# ---------------------------------------------------------------------------
# cross-query caching end to end
# ---------------------------------------------------------------------------

def test_second_identical_query_skips_planning_and_compile(tmp_path):
    s, _ = _serving_session(tmp_path)
    # result cache OFF: force the repeat onto the plan-cache path
    with _Server(s, **{"spark.rapids.serving.resultCache.maxBytes": "0"}
                 ) as srv:
        sub1 = srv.submit(Q_AGG)
        r1 = sub1.result(120)
        assert sub1.info["resolved"] == "planned"
        traces0 = SC.stats()["traces"]
        sub2 = srv.submit(Q_AGG)
        r2 = sub2.result(120)
        # plan-cache hit: NO planning, NO compilation, zero new traces
        assert sub2.info["resolved"] == "plan_cache"
        assert SC.stats()["traces"] - traces0 == 0
        assert r2 == r1
        assert srv.stats()["plan_cache"]["hits"] == 1


def test_literal_promoted_queries_share_structure(tmp_path):
    s, _ = _serving_session(tmp_path)
    with _Server(s, **{"spark.rapids.serving.resultCache.maxBytes": "0"}
                 ) as srv:
        srv.execute(Q_AGG)
        r_low = srv.execute(Q_AGG.replace("v > 0", "v > -10"))
        ps = srv.stats()["plan_cache"]
        # same normalized structure, new literal vector: shared entry
        assert ps["norm_hits"] == 1
        # and the literal actually took effect (more rows pass v > -10)
        assert sum(r["c"] for r in r_low) == 2000


def test_result_cache_hit_and_file_invalidation(tmp_path):
    s, path = _serving_session(tmp_path)
    with _Server(s) as srv:
        r1 = srv.execute(Q_AGG)
        sub = srv.submit(Q_AGG)
        assert sub.result(120) == r1
        assert sub.info["resolved"] == "result_cache"
        # rewrite an input file: both caches must invalidate, the query
        # recomputes over the new bytes
        t = pq.read_table(path)
        time.sleep(0.02)
        pq.write_table(t.slice(0, 500), path)
        r2 = srv.execute(Q_AGG)
        assert r2 != r1
        st = srv.stats()
        assert st["result_cache"]["invalidations"] >= 1
        assert st["plan_cache"]["invalidations"] >= 1
        # and the recomputed result is itself served from cache again
        sub3 = srv.submit(Q_AGG)
        assert sub3.result(120) == r2
        assert sub3.info["resolved"] == "result_cache"


def test_result_cache_hit_resolves_before_admission(tmp_path):
    # PR 15 deferral closed: a cached result consumes NO admission slot
    # — the probe runs before admit(), so hits neither wait for nor
    # hold device-memory reservations
    s, _ = _serving_session(tmp_path)
    with _Server(s) as srv:
        r1 = srv.execute(Q_AGG)
        admitted0 = srv.stats()["admission"]["admitted"]
        for _ in range(3):
            sub = srv.submit(Q_AGG)
            assert sub.result(120) == r1
            assert sub.info["resolved"] == "result_cache"
            # the hit still reports its latency decomposition
            assert sub.info["stages"]["lookup_s"] >= 0.0
        assert srv.stats()["admission"]["admitted"] == admitted0


def test_speculation_replay_never_reuses_poisoned_plan_state(tmp_path):
    # regression: a served query whose speculative join pair table
    # overflows (duplicate build keys -> more pairs than the probe
    # bucket) replays in exact mode.  The replay used to re-execute the
    # SAME physical-plan instance, whose exchange stores / join build
    # caches the failed speculative pass had filled with TRUNCATED
    # batches — silently wrong rows.  The replay must re-plan fresh
    # instances, and later plan-cache hits must never see the poisoned
    # ones.
    s, _ = _serving_session(tmp_path)
    nk = 9
    rng = np.random.default_rng(11)
    t_data = {"k": rng.integers(0, nk, 2000).astype(np.int64),
              "v": rng.standard_normal(2000)}
    dup_data = {"bk": np.repeat(np.arange(nk, dtype=np.int64), 5),
                "m": np.arange(nk * 5, dtype=np.int64)}
    # in-memory sides: this is the shape whose sub-partition hash join
    # provably poisons (the parquet-scan plan shape happens not to)
    s.create_or_replace_temp_view(
        "t", s.create_dataframe(dict(t_data), num_partitions=2))
    s.create_or_replace_temp_view(
        "u", s.create_dataframe(dict(dup_data), num_partitions=1))
    # GROUP BY u.m alone: hash(m) is NOT delivered by the join's
    # hash(k) partitioning, so a real exchange sits ABOVE the join and
    # its map side materializes the (truncated) join output — the
    # poison vector (the join-INPUT exchanges only ever hold clean
    # pre-join batches)
    q = ("SELECT u.m, SUM(t.v) AS sv FROM t JOIN u ON t.k = u.bk "
         "GROUP BY u.m ORDER BY u.m")

    # the shape must actually overflow (else this test asserts nothing):
    # 5 build rows per probe key >> the optimistic 1-match-per-row table
    from spark_rapids_tpu.ops.speculation import (SpeculationOverflow,
                                                  speculation_scope)
    df = s.sql(q)
    with pytest.raises(SpeculationOverflow):
        with speculation_scope() as ctx:
            df._executed_plan().collect_host()
            ctx.check()

    # CPU oracle on its OWN session (session.set_conf mutates in place —
    # flipping sql.enabled on ``s`` would quietly de-TPU the server too)
    cpu = tpu_session({"spark.rapids.sql.enabled": "false"})
    cpu.create_or_replace_temp_view(
        "t", cpu.create_dataframe(dict(t_data), num_partitions=2))
    cpu.create_or_replace_temp_view(
        "u", cpu.create_dataframe(dict(dup_data), num_partitions=1))
    expect = cpu.sql(q).collect()
    with _Server(s, **{"spark.rapids.serving.resultCache.maxBytes": "0"}
                 ) as srv:
        assert srv.execute(q) == expect          # replayed execution
        assert srv.execute(q) == expect          # plan-cache hit after


def test_failed_execution_discards_cached_plan_variant(tmp_path, monkeypatch):
    # an execution that fails AFTER planning may leave the cached plan's
    # exec instances with poisoned memoized state (e.g. a speculative
    # pass dying before its overflow check, stores built from truncated
    # joins) — the variant must be discarded, and the retry must plan
    # fresh, not hit the dirty instance
    import spark_rapids_tpu.session as SS
    s, _ = _serving_session(tmp_path)
    real = SS.collect_with_speculation
    calls = {"n": 0}

    def flaky(conf, factory):
        out = real(conf, factory)       # run fully (plan inserted+leased)
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected post-execution fault")
        return out

    monkeypatch.setattr(SS, "collect_with_speculation", flaky)
    with _Server(s, **{"spark.rapids.serving.resultCache.maxBytes": "0"}
                 ) as srv:
        with pytest.raises(RuntimeError, match="injected"):
            srv.execute(Q_AGG)
        assert srv.stats()["plan_cache"]["invalidations"] == 1
        sub = srv.submit(Q_AGG)
        rows = sub.result(120)
        assert sub.info["resolved"] == "planned"    # NOT a stale hit
        assert rows == s.sql(Q_AGG).collect()
        # and the fresh variant serves hits again
        sub2 = srv.submit(Q_AGG)
        assert sub2.result(120) == rows
        assert sub2.info["resolved"] == "plan_cache"


def test_set_conf_applies_serving_knobs_to_live_server(tmp_path):
    # regression: serving.* knobs set on a RUNNING server must apply to
    # the live structures — resultCache.maxBytes=0 used to leave the
    # constructed cache serving entries (only the conf snapshot changed)
    s, _ = _serving_session(tmp_path)
    with _Server(s) as srv:
        r1 = srv.execute(Q_AGG)
        srv.set_conf("spark.rapids.serving.resultCache.maxBytes", "0")
        assert srv.result_cache.max_bytes == 0
        sub = srv.submit(Q_AGG)
        assert sub.result(120) == r1
        # served by the PLAN cache now, never the disabled result cache
        assert sub.info["resolved"] == "plan_cache"
        assert srv.stats()["result_cache"]["hits"] == 0
        srv.set_conf("spark.rapids.serving.queueTimeoutMs", "123")
        assert srv.admission.timeout_ms == 123


def test_concurrent_bit_identity_mixed_workload(tmp_path):
    s, _ = _serving_session(tmp_path)
    with _Server(s, **{"spark.rapids.serving.maxConcurrentQueries": "4"}
                 ) as srv:
        serial = [srv.execute(q) for q in MIXED]
        # racing repeats (cache hits AND fresh plans: half the load runs
        # with caches bypassed via distinct literals) == serial rows
        subs = [(i % len(MIXED), srv.submit(MIXED[i % len(MIXED)]))
                for i in range(12)]
        for qi, sub in subs:
            assert sub.result(180) == serial[qi], MIXED[qi]


def test_uncacheable_query_still_serves(tmp_path):
    s, _ = _serving_session(tmp_path)
    with _Server(s) as srv:
        # DataFrame queries over in-memory sources sign (dev-cache
        # identity), so force unsignability through a callable attr
        df = s.sql(Q_AGG)
        df._plan.children[0].mystery_fn = lambda r: r
        r1 = srv.execute(df)
        assert r1 == srv.execute(s.sql(Q_AGG))
        ps = srv.stats()["plan_cache"]
        assert ps["hits"] == 0 and ps["inserts"] == 1   # only the signed run


# ---------------------------------------------------------------------------
# the online AutoTuner loop
# ---------------------------------------------------------------------------

def test_online_conf_delta_applies_to_next_admitted_query(tmp_path):
    s, _ = _serving_session(tmp_path)
    with _Server(s, **{"spark.rapids.serving.resultCache.maxBytes": "0"}
                 ) as srv:
        srv.execute(Q_AGG)
        # an online delta (batch size is plan-affecting) re-keys the plan
        # cache: the next admitted query re-plans under the new conf...
        srv.set_conf("spark.rapids.sql.batchSizeBytes", "32m")
        srv.execute(Q_AGG)
        ps = srv.stats()["plan_cache"]
        assert ps["inserts"] == 2 and ps["hits"] == 0
        # ...and later repeats under the same conf hit again
        srv.execute(Q_AGG)
        assert srv.stats()["plan_cache"]["hits"] == 1


def test_autotune_applied_delta_trail_and_semaphore_resize(tmp_path):
    from spark_rapids_tpu.memory.device_manager import get_runtime
    from spark_rapids_tpu.tools.autotune import Recommendation
    s, _ = _serving_session(tmp_path)
    with _Server(s, **{"spark.rapids.serving.autotune.enabled": "true"}
                 ) as srv:
        ring = EV.RingBufferSink(64)
        EV.add_global_sink(ring)
        try:
            old = int(srv.conf.get("spark.rapids.sql.concurrentGpuTasks"))
            rec = Recommendation(
                key="spark.rapids.sql.concurrentGpuTasks", current=old,
                recommended=old + 1, reason="unit", evidence=[],
                query_id=77)
            srv._apply_delta(rec, 77)
            assert int(srv.conf.get(
                "spark.rapids.sql.concurrentGpuTasks")) == old + 1
            key, was, now = srv.autotune_applied[-1][:3]
            assert key == "spark.rapids.sql.concurrentGpuTasks"
            assert int(was) == old and int(now) == old + 1
            evs = [e for e in ring.events()
                   if e.kind == "autotuneApplied"]
            assert evs and evs[-1].payload["new"] == str(old + 1)
            rt = get_runtime()
            if rt is not None:   # live budget follows the delta
                assert rt.semaphore.max_concurrent == old + 1
                rt.semaphore.resize(old)
            # an identical re-recommendation is a no-op (no event spam)
            n = len(srv.autotune_applied)
            srv._apply_delta(rec, 78)
            assert len(srv.autotune_applied) == n
            # the allowlist is explicit: only perf knobs tune online
            from spark_rapids_tpu.serving.server import ONLINE_TUNABLE_KEYS
            assert "spark.rapids.sql.enabled" not in ONLINE_TUNABLE_KEYS
            assert "spark.rapids.sql.batchSizeBytes" in ONLINE_TUNABLE_KEYS
        finally:
            EV.remove_global_sink(ring)


def test_autotune_loop_quiet_on_healthy_workload(tmp_path):
    s, _ = _serving_session(tmp_path)
    with _Server(s, **{"spark.rapids.serving.autotune.enabled": "true"}
                 ) as srv:
        for _ in range(3):
            srv.execute(Q_GROUP2)
        # rules run after every query; a healthy small workload yields
        # no deltas (quiet-on-healthy), and tuning never fails a query
        assert srv.autotune_applied == []
        # repeats resolve from the result cache BEFORE admission: only
        # the first execution consumed an admission slot
        assert srv.stats()["admission"]["admitted"] == 1


def test_semaphore_resize_grow_wakes_and_shrink_drains():
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    sem = TpuSemaphore(1)
    try:
        sem.acquire_if_necessary(task_id=1)
        got = threading.Event()

        def waiter():
            sem.acquire_if_necessary(task_id=2)
            got.set()

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        assert not got.wait(0.15)    # budget 1: second acquire queues
        assert sem.resize(2) == 1    # grow applies ONLINE, wakes waiter
        assert got.wait(5.0)
        t.join(5.0)
        # shrink never revokes held permits: drains as holders release
        assert sem.resize(1) == 2
        assert sem.max_concurrent == 1
        assert sem.resize(1) == 1    # no-op resize
    finally:
        sem.release_all(task_id=1)
        sem.release_all(task_id=2)
    assert not sem.stats()["holders"]


# ---------------------------------------------------------------------------
# PR 15 satellites
# ---------------------------------------------------------------------------

class _CountingSource:
    """Minimal host exec: counts how often its stream is (re)built."""

    def __init__(self):
        from spark_rapids_tpu.plan.base import Exec
        self.node = Exec()
        self.builds = 0

        def execute_partition(pidx):
            self.builds += 1
            yield ("batch", pidx)
        self.node.execute_partition = execute_partition


def test_cte_cache_rebuilds_per_execution_epoch():
    from spark_rapids_tpu.exec.basic import (CpuCteCacheExec,
                                             refresh_cte_epochs)
    src = _CountingSource()
    cte = CpuCteCacheExec(src.node)
    # two pulls within one epoch: ONE materialization, shared
    assert list(cte.execute_partition(0)) == [("batch", 0)]
    assert list(cte.execute_partition(0)) == [("batch", 0)]
    assert src.builds == 1
    # a new prepared action stamps a fresh epoch: stale batches (changed
    # files, speculation replay, plan-cache re-execution) never replay
    refresh_cte_epochs(cte)
    assert list(cte.execute_partition(0)) == [("batch", 0)]
    assert src.builds == 2
    refresh_cte_epochs(cte)
    assert list(cte.execute_partition(0)) == [("batch", 0)]
    assert src.builds == 3


def test_concat_padding_guard_sizes_from_forced_counts(monkeypatch):
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar.batch import batch_from_pydict
    from spark_rapids_tpu.columnar.column import DeferredCount
    from spark_rapids_tpu.ops import batch_ops as BO

    def sparse(lo):
        db = batch_from_pydict(
            {"x": np.arange(lo, lo + 2000, dtype=np.int64)}).to_device()
        keep = np.zeros(db.bucket, dtype=bool)
        keep[:2000][::667] = True            # 3 live rows in a 2048 bucket
        return BO.compact_batch(db, jnp.asarray(keep))

    a, b = sparse(0), sparse(5000)
    assert isinstance(a.row_count, DeferredCount) and \
        not a.row_count.is_forced
    # default: deferred sizing = next-pow2 of summed padded buckets
    out0 = BO.concat_batches([sparse(0), sparse(5000)])
    assert out0.bucket >= 4096
    # above the byte threshold: force the counts once, shrink the
    # padded inputs, size the output from LIVE rows (OOM guard)
    monkeypatch.setattr(BO, "CONCAT_FORCE_SYNC_BYTES", 0)
    out1 = BO.concat_batches([a, b])
    assert out1.bucket < 4096
    rows = sorted(out1.to_host().to_pydict()["x"])
    assert rows == [0, 667, 1334, 5000, 5667, 6334]
    assert rows == sorted(out0.to_host().to_pydict()["x"])


class _FakeBatch:
    def __init__(self, nbytes):
        self._n = nbytes

    def nbytes(self):
        return self._n


class _FakeProbe:
    """Streams fake batches, recording pulls and close."""

    def __init__(self, sizes):
        self.sizes = sizes
        self.pulled = 0
        self.closed = False

    def execute_partition(self, pidx):
        try:
            for s in self.sizes:
                self.pulled += 1
                yield _FakeBatch(s)
        finally:
            self.closed = True


def _swap_join(probe, max_bytes=1 << 30):
    from spark_rapids_tpu.exec.joins import TpuShuffledHashJoinExec
    from spark_rapids_tpu.ops import join_ops as J
    j = object.__new__(TpuShuffledHashJoinExec)
    j.join_type = J.INNER
    j.condition = None
    j.left_keys = ["k"]
    j.build_swap_enabled = True
    j.build_swap_max_bytes = max_bytes
    j.children = [probe, None]       # .left rides children[0]
    return j


def test_build_swap_samples_first_batches_only():
    # probe provably bigger after TWO batches: sampling stops there
    # (the old code materialized the ENTIRE probe partition to weigh a
    # swap it doesn't take)
    probe = _FakeProbe([600, 600, 600, 600, 600])
    j = _swap_join(probe)
    build = [_FakeBatch(1000)]
    it, out_build, swapped = j._maybe_swapped_with(build, 0)
    assert not swapped and out_build is build
    assert probe.pulled == 2
    # the sampled prefix replays first, then the live stream continues
    drained = list(it)
    assert len(drained) == 5 and probe.closed
    # abandoning the stream early still closes the child
    probe2 = _FakeProbe([600, 600, 600, 600])
    it2, _, _ = _swap_join(probe2)._maybe_swapped_with(
        [_FakeBatch(1000)], 0)
    next(it2)
    it2.close()
    assert probe2.closed


def test_build_swap_takes_smaller_probe_as_build():
    probe = _FakeProbe([100, 100])
    j = _swap_join(probe)
    big_build = [_FakeBatch(5000)]
    it, out_build, swapped = j._maybe_swapped_with(big_build, 0)
    assert swapped            # whole probe drained and is the smaller side
    assert [b._n for b in out_build] == [100, 100]
    assert [b._n for b in it] == [5000]


def test_conf_module_global_lint_rule(tmp_path):
    import textwrap

    from spark_rapids_tpu.tools.lint.core import run_lint
    from spark_rapids_tpu.tools.lint.rules import ConfModuleGlobalRule
    (tmp_path / "bad_mod.py").write_text(textwrap.dedent("""\
        import spark_rapids_tpu.exec.joins as _XJ

        def apply(conf):
            _XJ.BUILD_SWAP_ENABLED = conf.get("spark.rapids.x")
    """))
    (tmp_path / "clean_mod.py").write_text(textwrap.dedent("""\
        def convert(p, m):
            out = make_exec()
            out.build_swap_enabled = m.conf.get("spark.rapids.x")
            LOCAL_CONST = 5
            return out
    """))
    report = run_lint(root=str(tmp_path), rules=[ConfModuleGlobalRule()],
                      baseline_path="")
    findings = [f for f in report.findings
                if f.rule == "conf-module-global"]
    assert len(findings) == 1 and "bad_mod.py" in findings[0].file
    assert "BUILD_SWAP_ENABLED" in findings[0].message
