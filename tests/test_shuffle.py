"""Shuffle exchange + partitioning tests (differential CPU vs TPU, the
reference methodology; multi-partition placement correctness)."""

import numpy as np
import pytest

from tests.asserts import (assert_tpu_and_cpu_are_equal_collect, cpu_session,
                           tpu_session)


def _df(s, n=10_000, parts=4):
    rng = np.random.default_rng(3)
    return s.create_dataframe(
        {"k": rng.integers(0, 50, n), "v": rng.normal(size=n),
         "s": [f"r{i % 97}" for i in range(n)]},
        num_partitions=parts)


def test_hash_repartition_preserves_rows():
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).repartition(8, "k"), ignore_order=True)


def test_hash_repartition_groups_keys_together():
    s = tpu_session()
    df = _df(s).repartition(8, "k")
    from spark_rapids_tpu.plan.overrides import TpuOverrides
    plan = TpuOverrides(s.conf).apply(df._plan)
    seen = {}
    for p in range(plan.num_partitions):
        from spark_rapids_tpu.plan.base import run_task
        for b in run_task(plan, p):
            from spark_rapids_tpu.columnar.batch import ColumnarBatch
            hb = b.to_host() if isinstance(b, ColumnarBatch) else b
            for k in set(hb.to_pydict()["k"]):
                assert seen.setdefault(k, p) == p, \
                    f"key {k} split across partitions {seen[k]} and {p}"


def test_round_robin_repartition():
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).repartition(5), ignore_order=True)


def test_coalesce_to_one():
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).coalesce(1), ignore_order=True)


def test_global_order_by_ints():
    def f(s):
        return _df(s).order_by("k")
    cpu = f(cpu_session()).to_pydict()["k"]
    tpu = f(tpu_session()).to_pydict()["k"]
    assert cpu == sorted(cpu)
    assert tpu == cpu


def test_global_order_by_desc_strings():
    from spark_rapids_tpu.functions import desc

    def f(s):
        return _df(s, n=3000).order_by(desc("s"))
    cpu = f(cpu_session()).to_pydict()["s"]
    tpu = f(tpu_session()).to_pydict()["s"]
    assert cpu == sorted(cpu, reverse=True)
    assert tpu == cpu


def test_global_order_by_floats_with_secondary_key():
    from spark_rapids_tpu.functions import asc, desc

    def f(s):
        return _df(s, n=5000, parts=3).order_by(asc("k"), desc("v"))
    cpu = f(cpu_session()).collect()
    tpu = f(tpu_session()).collect()
    assert cpu == tpu


def test_exchange_empty_input():
    def f(s):
        df = s.create_dataframe({"a": np.array([], dtype=np.int64)})
        return df.repartition(4, "a")
    assert_tpu_and_cpu_are_equal_collect(f, ignore_order=True)


def test_order_by_single_partition_input():
    def f(s):
        return _df(s, n=500, parts=1).order_by("k")
    cpu = f(cpu_session()).to_pydict()["k"]
    tpu = f(tpu_session()).to_pydict()["k"]
    assert tpu == cpu == sorted(cpu)


def test_coalesce_is_shuffle_free_merge():
    s = tpu_session()
    df = _df(s, parts=8).coalesce(3)
    assert df._plan.num_partitions == 3
    # never increases the count (Spark contract)
    assert _df(s, parts=2).coalesce(8)._plan.num_partitions == 2
    assert_tpu_and_cpu_are_equal_collect(
        lambda s2: _df(s2, parts=8).coalesce(3), ignore_order=True)
