"""Cross-PROCESS shuffle tests: real batches move between two worker OS
processes over the TCP socket transport, and a killed peer produces a
fetch failure that a replacement worker recovers from.

This goes one step past the reference's transport tests (mocked UCX,
tests/.../shuffle/RapidsShuffleClientSuite.scala): the protocol stack runs
over a genuine process + network boundary (VERDICT r1 item #5).
"""

import multiprocessing as mp
import time

import pytest

from spark_rapids_tpu.shuffle.heartbeat import ShuffleHeartbeatManager

_CTX = mp.get_context("spawn")


class _Worker:
    def __init__(self, executor_id: str, port: int = 0):
        from spark_rapids_tpu.shuffle.worker import run_worker
        self.executor_id = executor_id
        self.conn, child = _CTX.Pipe()
        self.proc = _CTX.Process(target=run_worker,
                                 args=(executor_id, port, child),
                                 daemon=True)
        self.proc.start()
        kind, eid, endpoint = self._recv_non_hb(timeout=30)
        assert kind == "ready" and eid == executor_id
        self.endpoint = endpoint
        host, port_s = endpoint.split(":")
        self.addr = (host, int(port_s))

    def _recv_non_hb(self, timeout=30):
        deadline = time.monotonic() + timeout
        while True:
            remain = deadline - time.monotonic()
            if remain <= 0 or not self.conn.poll(remain):
                raise TimeoutError(f"no reply from {self.executor_id}")
            msg = self.conn.recv()
            if msg[0] != "hb":
                return msg

    def drain_heartbeats(self, manager: ShuffleHeartbeatManager):
        while self.conn.poll(0):
            msg = self.conn.recv()
            if msg[0] == "hb":
                try:
                    manager.executor_heartbeat(msg[1])
                except KeyError:
                    manager.register_executor(msg[1], msg[2])

    def cmd(self, *args, timeout=30):
        self.conn.send(args)
        return self._recv_non_hb(timeout)

    def kill(self):
        self.proc.kill()
        self.proc.join(10)

    def stop(self):
        if self.proc.is_alive():
            try:
                self.conn.send(("exit",))
                self._recv_non_hb(timeout=5)
            except Exception:
                pass
            self.proc.join(5)
            if self.proc.is_alive():
                self.proc.kill()


@pytest.fixture
def two_workers():
    a = _Worker("exec-a")
    b = _Worker("exec-b")
    yield a, b
    a.stop()
    b.stop()


def test_batches_move_between_processes(two_workers):
    a, b = two_workers
    peers = {a.executor_id: a.addr, b.executor_id: b.addr}
    assert a.cmd("peers", peers)[0] == "peers_ok"
    assert b.cmd("peers", peers)[0] == "peers_ok"

    kind, rows, ksum = a.cmd("load", 7, 0, 3, 501, 42)
    assert kind == "loaded" and rows == 501

    kind, got_rows, got_ksum = b.cmd("fetch", "exec-a", 7, 3)
    assert kind == "ok", (kind, got_rows)
    assert got_rows == rows
    assert got_ksum == ksum


def test_killed_peer_fetch_failure_and_recovery(two_workers):
    a, b = two_workers
    manager = ShuffleHeartbeatManager(timeout_s=1.0)
    peers = {a.executor_id: a.addr, b.executor_id: b.addr}
    a.cmd("peers", peers)
    b.cmd("peers", peers)
    a.cmd("load", 9, 0, 1, 200, 7)
    time.sleep(0.5)   # let one heartbeat interval elapse for both workers
    a.drain_heartbeats(manager)
    b.drain_heartbeats(manager)
    assert {e.executor_id for e in manager.live_executors()} == \
        {"exec-a", "exec-b"}

    # first fetch works
    kind, rows, ksum = b.cmd("fetch", "exec-a", 9, 1)
    assert kind == "ok" and rows == 200

    # kill the serving peer: the next fetch must FAIL, not hang
    a.kill()
    kind, detail = b.cmd("fetch", "exec-a", 9, 1, timeout=60)
    assert kind == "fetch_failed", (kind, detail)

    # heartbeat expiry notices the death (driver-side liveness)
    time.sleep(1.2)
    b.drain_heartbeats(manager)
    assert "exec-a" in manager.expire_dead()

    # recovery: a replacement executor re-registers at a new endpoint with
    # the same map output; the client retries and succeeds (the engine's
    # stage-retry story: fetch failure -> regenerate -> refetch)
    a2 = _Worker("exec-a")
    try:
        a2.cmd("peers", {b.executor_id: b.addr,
                         a2.executor_id: a2.addr})
        a2.cmd("load", 9, 0, 1, 200, 7)
        b.cmd("peers", {a2.executor_id: a2.addr, b.executor_id: b.addr})
        kind, rows2, ksum2 = b.cmd("fetch", "exec-a", 9, 1)
        assert kind == "ok" and rows2 == 200 and ksum2 == ksum
    finally:
        a2.stop()
