"""Shuffle transport suite.

Mirrors the reference's multi-node-without-a-cluster strategy
(tests/.../shuffle/RapidsShuffleClientSuite.scala — Mockito-mocked
transport exercising client/server state machines; WindowedBlockIteratorSuite;
RapidsShuffleHeartbeatManagerSuite)."""

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from spark_rapids_tpu.columnar.batch import batch_from_pydict
from spark_rapids_tpu.shuffle.catalog import (ShuffleBlockId,
                                              ShuffleBufferCatalog,
                                              ShuffleReceivedBufferCatalog)
from spark_rapids_tpu.shuffle.client_server import (BufferSendState,
                                                    ShuffleClient,
                                                    ShuffleServer)
from spark_rapids_tpu.shuffle.heartbeat import (ExecutorHeartbeatEndpoint,
                                                ShuffleHeartbeatManager)
from spark_rapids_tpu.shuffle.protocol import (BlockFrameHeader, BlockMeta,
                                               MetadataRequest,
                                               MetadataResponse,
                                               TransferRequest,
                                               TransferResponse,
                                               decode_message, encode_message)
from spark_rapids_tpu.shuffle.serializer import (deserialize_batch,
                                                 serialize_batch)
from spark_rapids_tpu.shuffle.threaded import (BytesInFlightLimiter,
                                               ThreadedShuffleReader,
                                               ThreadedShuffleWriter)
from spark_rapids_tpu.shuffle.transport import (BounceBufferManager,
                                                Connection,
                                                InProcessTransport,
                                                Transaction,
                                                TransactionStatus,
                                                WindowedBlockIterator)


def _hb(n=100, seed=0):
    rng = np.random.default_rng(seed)
    return batch_from_pydict({
        "k": rng.integers(0, 50, n).astype(np.int64),
        "s": [f"row-{i}" if i % 7 else None for i in range(n)],
    })


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

def test_serializer_roundtrip_codecs():
    hb = _hb(257)
    for codec in ("none", "lz4"):
        data = serialize_batch(hb, codec)
        back = deserialize_batch(data)
        assert back.to_pydict() == hb.to_pydict()
    assert len(serialize_batch(hb, "lz4")) < len(serialize_batch(hb, "none"))


def test_protocol_message_roundtrips():
    b = ShuffleBlockId(3, 7, 11)
    for msg in (
        MetadataRequest(1, 3, 11),
        MetadataResponse(1, (BlockMeta(b, 1024, 2),)),
        TransferRequest(2, (b, ShuffleBlockId(3, 8, 11))),
        TransferResponse(2, True),
        TransferResponse(3, False, "boom"),
        BlockFrameHeader(2, b, 0, 2, 512),
    ):
        back = decode_message(encode_message(msg))
        assert back == msg


# ---------------------------------------------------------------------------
# windowed iteration + bounce buffers (WindowedBlockIteratorSuite analog)
# ---------------------------------------------------------------------------

def test_windowed_block_iterator_packs_and_spans():
    b = [(ShuffleBlockId(1, 0, 0), 100), (ShuffleBlockId(1, 1, 0), 50),
         (ShuffleBlockId(1, 2, 0), 300)]
    windows = list(WindowedBlockIterator(b, 128))
    # window1: 100 of b0 + 28 of b1; window2: 22 of b1 + 106 of b2; ...
    flat = [(r.block.map_id, r.offset, r.length) for w in windows for r in w]
    total_by_block = {}
    for m, off, ln in flat:
        total_by_block[m] = total_by_block.get(m, 0) + ln
    assert total_by_block == {0: 100, 1: 50, 2: 300}
    for w in windows:
        assert sum(r.length for r in w) <= 128
    # each block's ranges are contiguous and ascending
    seen_end = {}
    for m, off, ln in flat:
        assert off == seen_end.get(m, 0)
        seen_end[m] = off + ln
    last = windows[-1][-1]
    assert last.is_final


def test_windowed_block_iterator_skips_empty_blocks():
    b = [(ShuffleBlockId(1, 0, 0), 0), (ShuffleBlockId(1, 1, 0), 10)]
    windows = list(WindowedBlockIterator(b, 64))
    assert len(windows) == 1 and len(windows[0]) == 1
    assert windows[0][0].block.map_id == 1


def test_bounce_buffer_pool_blocks_when_exhausted():
    mgr = BounceBufferManager(buffer_size=16, count=2)
    a = mgr.acquire()
    b = mgr.acquire()
    assert mgr.available == 0
    with pytest.raises(TimeoutError):
        mgr.acquire(timeout=0.05)
    a.close()
    c = mgr.acquire(timeout=1)
    assert mgr.available == 0
    b.close()
    c.close()
    assert mgr.available == 2


# ---------------------------------------------------------------------------
# client/server over a MOCKED transport (RapidsShuffleClientSuite analog)
# ---------------------------------------------------------------------------

class MockConnection(Connection):
    """Scripted connection: records requests, returns canned responses."""

    def __init__(self):
        super().__init__("mock-peer")
        self.requests = []
        self.responses = []
        self.data_frames = []

    def request(self, message, cb=None):
        self.requests.append(decode_message(message))
        txn = self._new_txn().start(cb)
        if self.responses:
            status, payload = self.responses.pop(0)
            txn.complete(status, response=payload)
        else:
            txn.complete(TransactionStatus.ERROR, error="no scripted reply")
        return txn

    def send_data(self, header, payload, cb=None):
        self.data_frames.append((decode_message(header), bytes(payload)))
        txn = self._new_txn().start(cb)
        txn.complete(TransactionStatus.SUCCESS)
        return txn


class MockTransport:
    def __init__(self, conn):
        self.conn = conn

    def connect(self, peer):
        return self.conn


def test_client_metadata_flow_with_mock():
    conn = MockConnection()
    b = ShuffleBlockId(5, 0, 2)
    conn.responses.append((TransactionStatus.SUCCESS, encode_message(
        MetadataResponse(1, (BlockMeta(b, 64, 1),)))))
    client = ShuffleClient("c", MockTransport(conn))

    class FakeServer:
        executor_id = "mock-peer"
    resp = client.fetch_metadata(FakeServer(), 5, 2)
    assert resp.blocks[0].block == b
    assert isinstance(conn.requests[0], MetadataRequest)
    assert conn.requests[0].shuffle_id == 5


def test_client_surfaces_transport_errors():
    conn = MockConnection()   # no scripted responses -> ERROR
    client = ShuffleClient("c", MockTransport(conn))

    class FakeServer:
        executor_id = "mock-peer"
    with pytest.raises(ConnectionError, match="no scripted reply"):
        client.fetch_metadata(FakeServer(), 1, 0)


def test_client_detects_short_transfer():
    """Transfer acked but fewer data frames arrived than metadata promised
    (the reference's degenerate-buffer case).  Retries pinned to zero so
    the short-transfer cause surfaces directly (the retry wrapper would
    otherwise re-attempt and wrap it in ShuffleFetchFailed)."""
    from spark_rapids_tpu.shuffle.client_server import FetchRetryPolicy
    conn = MockConnection()
    b = ShuffleBlockId(5, 0, 2)
    conn.responses.append((TransactionStatus.SUCCESS, encode_message(
        MetadataResponse(1, (BlockMeta(b, 64, 2),)))))
    conn.responses.append((TransactionStatus.SUCCESS, encode_message(
        TransferResponse(2, True))))
    client = ShuffleClient("c", MockTransport(conn),
                           retry=FetchRetryPolicy(timeout_s=0.2,
                                                  max_retries=0))

    class FakeServer:
        executor_id = "mock-peer"

        def note_reply_to(self, req_id, peer):
            pass
    with pytest.raises(ConnectionError, match="short transfer"):
        client.do_fetch(FakeServer(), 5, 2)


def test_buffer_send_state_chunks_through_bounce_buffers():
    catalog = ShuffleBufferCatalog()
    block = ShuffleBlockId(1, 0, 0)
    hb = _hb(500)
    catalog.add_batch(block, hb)
    bounce = BounceBufferManager(buffer_size=128, count=2)
    conn = MockConnection()
    state = BufferSendState(9, [block], catalog, bounce)
    while not state.done:
        state.send_next(conn)
    # every chunk <= the bounce window; offsets tile the frame exactly
    assert len(conn.data_frames) > 1
    total = conn.data_frames[0][0].total_bytes
    acc = bytearray(total)
    covered = 0
    for header, payload in conn.data_frames:
        assert header.block == block and header.frame_count == 1
        assert header.nbytes == len(payload) <= 128
        acc[header.chunk_offset:header.chunk_offset + header.nbytes] = \
            payload
        covered += header.nbytes
    assert covered == total
    assert deserialize_batch(bytes(acc)).to_pydict() == hb.to_pydict()
    assert bounce.available == 2          # all returned to the pool


# ---------------------------------------------------------------------------
# end-to-end over the in-process transport
# ---------------------------------------------------------------------------

def test_full_fetch_in_process():
    transport = InProcessTransport()
    catalog = ShuffleBufferCatalog(codec="lz4")
    server = ShuffleServer("exec-A", catalog, transport)
    client = ShuffleClient("exec-B", transport)
    transport.register_handler("exec-A", server)
    transport.register_handler("exec-B", client)

    hb1, hb2 = _hb(300, 1), _hb(200, 2)
    catalog.add_batch(ShuffleBlockId(7, 0, 3), hb1)
    catalog.add_batch(ShuffleBlockId(7, 1, 3), hb2)
    catalog.add_batch(ShuffleBlockId(7, 0, 4), _hb(50, 3))  # other partition

    blocks = client.do_fetch(server, 7, 3)
    assert len(blocks) == 2
    got = [b for blk in blocks for b in client.received.read_batches(blk)]
    assert got[0].to_pydict() == hb1.to_pydict()
    assert got[1].to_pydict() == hb2.to_pydict()


def test_fetch_empty_partition_returns_no_blocks():
    transport = InProcessTransport()
    catalog = ShuffleBufferCatalog()
    server = ShuffleServer("exec-A", catalog, transport)
    client = ShuffleClient("exec-B", transport)
    transport.register_handler("exec-A", server)
    transport.register_handler("exec-B", client)
    assert client.do_fetch(server, 1, 0) == []


# ---------------------------------------------------------------------------
# heartbeats (RapidsShuffleHeartbeatManagerSuite analog)
# ---------------------------------------------------------------------------

def test_heartbeat_registration_and_delta_dissemination():
    clock = [0.0]
    mgr = ShuffleHeartbeatManager(timeout_s=10, clock=lambda: clock[0])
    assert mgr.register_executor("e1") == []
    peers_of_e2 = mgr.register_executor("e2")
    assert [p.executor_id for p in peers_of_e2] == ["e1"]
    # e1's next heartbeat learns about e2, exactly once
    new = mgr.executor_heartbeat("e1")
    assert [p.executor_id for p in new] == ["e2"]
    assert mgr.executor_heartbeat("e1") == []
    mgr.register_executor("e3")
    assert [p.executor_id for p in mgr.executor_heartbeat("e1")] == ["e3"]


def test_heartbeat_expiry():
    clock = [0.0]
    mgr = ShuffleHeartbeatManager(timeout_s=5, clock=lambda: clock[0])
    mgr.register_executor("e1")
    mgr.register_executor("e2")
    clock[0] = 4.0
    mgr.executor_heartbeat("e2")
    clock[0] = 7.0
    assert mgr.expire_dead() == ["e1"]
    assert [e.executor_id for e in mgr.live_executors()] == ["e2"]
    with pytest.raises(KeyError):
        mgr.executor_heartbeat("e1")


def test_heartbeat_expiry_full_lifecycle():
    """register -> miss heartbeats -> expire (workerExpired event +
    expiry listeners fired) -> re-register rejoins cleanly."""
    from spark_rapids_tpu.aux.events import RingBufferSink, add_global_sink, \
        remove_global_sink
    clock = [0.0]
    mgr = ShuffleHeartbeatManager(timeout_s=5, clock=lambda: clock[0])
    invalidated = []
    mgr.add_expiry_listener(invalidated.append)
    mgr.register_executor("e1", endpoint="h1:1")
    mgr.register_executor("e2", endpoint="h2:2")
    # e2 keeps heartbeating, e1 goes silent
    clock[0] = 4.0
    mgr.executor_heartbeat("e2")
    sink = RingBufferSink()
    add_global_sink(sink)
    try:
        clock[0] = 7.0
        assert mgr.expire_dead() == ["e1"]
    finally:
        remove_global_sink(sink)
    assert invalidated == ["e1"]
    kinds = [e.kind for e in sink.events()]
    assert "workerExpired" in kinds
    ev = next(e for e in sink.events() if e.kind == "workerExpired")
    assert ev.payload["executor_id"] == "e1"
    # a second sweep is idempotent
    assert mgr.expire_dead() == []
    assert invalidated == ["e1"]
    # re-registration (worker restart at a new endpoint) rejoins: e2's
    # next heartbeat learns the NEW incarnation
    peers = mgr.register_executor("e1", endpoint="h1:99")
    assert [p.executor_id for p in peers] == ["e2"]
    new = mgr.executor_heartbeat("e2")
    assert [(p.executor_id, p.endpoint) for p in new] == [("e1", "h1:99")]
    assert {e.executor_id for e in mgr.live_executors()} == {"e1", "e2"}


def test_heartbeat_expiry_listener_failure_does_not_block():
    clock = [0.0]
    mgr = ShuffleHeartbeatManager(timeout_s=1, clock=lambda: clock[0])
    seen = []
    mgr.add_expiry_listener(lambda eid: 1 / 0)     # broken listener
    mgr.add_expiry_listener(seen.append)
    mgr.register_executor("e1")
    clock[0] = 5.0
    assert mgr.expire_dead() == ["e1"]
    assert seen == ["e1"]


def test_catalog_drop_owner_invalidates_blocks():
    cat = ShuffleBufferCatalog()
    cat.add_batch(ShuffleBlockId(1, 0, 0), _hb(10), owner="e1")
    cat.add_batch(ShuffleBlockId(1, 1, 0), _hb(10), owner="e2")
    cat.add_frame(ShuffleBlockId(1, 2, 0), b"x")   # ownerless (local)
    dropped = cat.drop_owner("e1")
    assert dropped == [ShuffleBlockId(1, 0, 0)]
    assert cat.frames(ShuffleBlockId(1, 0, 0)) == []
    assert cat.frames(ShuffleBlockId(1, 1, 0)) != []
    assert cat.frames(ShuffleBlockId(1, 2, 0)) == [b"x"]
    assert cat.drop_owner("e1") == []              # idempotent


def test_heartbeat_endpoint_wires_new_peers():
    mgr = ShuffleHeartbeatManager()
    seen = []
    ep1 = ExecutorHeartbeatEndpoint("e1", mgr, on_new_peer=seen.append)
    ep1.register()
    assert seen == []
    mgr.register_executor("e2")
    ep1.heartbeat()
    assert [p.executor_id for p in seen] == ["e2"]
    ep1.heartbeat()
    assert len(seen) == 1


# ---------------------------------------------------------------------------
# multithreaded writer/reader
# ---------------------------------------------------------------------------

def test_threaded_writer_reader_roundtrip(tmp_path):
    pool = ThreadPoolExecutor(4)
    hb_by_part = {0: _hb(100, 10), 2: _hb(60, 11)}
    writer = ThreadedShuffleWriter(1, 0, 4, pool, directory=str(tmp_path),
                                   codec="lz4")
    out = writer.write(list(hb_by_part.items()))
    assert out.partition_bytes(1) == 0 and out.partition_bytes(3) == 0
    reader = ThreadedShuffleReader(pool)
    got0 = list(reader.read([out], 0))
    assert got0[0].to_pydict() == hb_by_part[0].to_pydict()
    got2 = list(reader.read([out], 2))
    assert got2[0].to_pydict() == hb_by_part[2].to_pydict()
    assert list(reader.read([out], 1)) == []
    pool.shutdown()


def test_bytes_in_flight_limiter_blocks():
    lim = BytesInFlightLimiter(100)
    lim.acquire(80)
    state = {"acquired": False}

    def second():
        lim.acquire(50)
        state["acquired"] = True
        lim.release(50)

    t = threading.Thread(target=second)
    t.start()
    t.join(0.1)
    assert not state["acquired"]       # blocked: 80 + 50 > 100
    lim.release(80)
    t.join(2)
    assert state["acquired"]
    assert lim.in_flight == 0


def test_oversized_payload_still_progresses():
    lim = BytesInFlightLimiter(10)
    lim.acquire(50)      # larger than the cap but nothing else in flight
    assert lim.in_flight == 50
    lim.release(50)


# ---------------------------------------------------------------------------
# end-to-end through the query engine per shuffle mode
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["DEFAULT", "MULTITHREADED", "CACHED"])
def test_exchange_modes_differential(mode):
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.expressions.base import Alias, col
    from tests.asserts import assert_tpu_and_cpu_are_equal_collect
    rng = np.random.default_rng(5)
    data = {"g": rng.integers(0, 17, 4000).astype(np.int64),
            "v": rng.standard_normal(4000)}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(data, num_partitions=4)
        .group_by("g").agg(Alias(F.sum(col("v")), "sv"),
                           Alias(F.count(col("v")), "c")),
        ignore_order=True, approx_float=True,
        conf={"spark.rapids.shuffle.mode": mode,
              "spark.rapids.shuffle.compression.codec":
                  "lz4" if mode != "DEFAULT" else "none"})
