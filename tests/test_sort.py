"""Sort tests: device sort vs CPU oracle (differential, reference
methodology: assert_gpu_and_cpu_are_equal_collect)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import batch_from_pydict
from spark_rapids_tpu.exec.basic import CpuInMemoryScanExec
from spark_rapids_tpu.exec.sort import CpuSortExec, SortSpec, TpuSortExec
from spark_rapids_tpu.expressions.base import BoundReference, col, lit
from tests.asserts import assert_batches_equal


def _scan(d, schema=None):
    hb = batch_from_pydict(d, schema)
    return CpuInMemoryScanExec([[hb]], hb.schema)


def _run_both(scan, specs):
    cpu = CpuSortExec(specs, scan).collect_host()
    tpu_plan = TpuSortExec(specs, scan)
    from spark_rapids_tpu.plan.overrides import insert_transitions
    from spark_rapids_tpu.config import default_conf
    tpu = insert_transitions(tpu_plan, default_conf()).collect_host()
    assert_batches_equal(cpu, tpu, check_order=True)
    return cpu


def _ref(i, dt=T.LONG):
    return BoundReference(i, dt, True)


def test_sort_ints_asc_desc(rng):
    vals = rng.integers(-1000, 1000, 5000)
    scan = _scan({"a": vals, "b": np.arange(5000)})
    _run_both(scan, [SortSpec(_ref(0), ascending=True)])
    _run_both(scan, [SortSpec(_ref(0), ascending=False)])


def test_sort_with_nulls():
    a = pa.array([5, None, 3, None, 1, 2, None, 4], type=pa.int64())
    tbl = pa.table({"a": a, "b": pa.array(range(8), type=pa.int64())})
    from spark_rapids_tpu.columnar.batch import batch_from_arrow
    hb = batch_from_arrow(tbl)
    scan = CpuInMemoryScanExec([[hb]], hb.schema)
    out = _run_both(scan, [SortSpec(_ref(0), True)])   # nulls first
    assert out.to_pydict()["a"][:3] == [None, None, None]
    out = _run_both(scan, [SortSpec(_ref(0), False)])  # desc: nulls last
    assert out.to_pydict()["a"][-3:] == [None, None, None]
    _run_both(scan, [SortSpec(_ref(0), True, nulls_first=False)])
    _run_both(scan, [SortSpec(_ref(0), False, nulls_first=True)])


def test_sort_multi_key_stable(rng):
    a = rng.integers(0, 10, 3000)
    b = rng.integers(-50, 50, 3000)
    c = np.arange(3000)
    scan = _scan({"a": a, "b": b, "c": c})
    _run_both(scan, [SortSpec(_ref(0), True), SortSpec(_ref(1), False)])
    _run_both(scan, [SortSpec(_ref(0), False), SortSpec(_ref(1), True)])


def test_sort_floats_nan_inf(rng):
    vals = np.array([1.5, -0.0, 0.0, np.nan, np.inf, -np.inf, -2.25, np.nan,
                     3.75, -1e300])
    scan = _scan({"a": vals, "i": np.arange(10)},
                 T.StructType([T.StructField("a", T.DOUBLE),
                               T.StructField("i", T.LONG)]))
    out = _run_both(scan, [SortSpec(_ref(0, T.DOUBLE), True)])
    d = out.to_pydict()["a"]
    # Spark: NaN sorts greater than +inf
    assert np.isnan(d[-1]) and np.isnan(d[-2])
    assert d[-3] == np.inf


def test_sort_float32(rng):
    vals = rng.normal(size=2000).astype(np.float32)
    scan = _scan({"a": vals},
                 T.StructType([T.StructField("a", T.FLOAT)]))
    _run_both(scan, [SortSpec(_ref(0, T.FLOAT), True)])
    _run_both(scan, [SortSpec(_ref(0, T.FLOAT), False)])


def test_sort_strings():
    strs = ["banana", "", "apple", "app", "apples", "cherry", None, "a",
            "Banana", "\x00zero", "zz", None]
    tbl = pa.table({"s": pa.array(strs, type=pa.string()),
                    "i": pa.array(range(len(strs)), type=pa.int64())})
    from spark_rapids_tpu.columnar.batch import batch_from_arrow
    hb = batch_from_arrow(tbl)
    scan = CpuInMemoryScanExec([[hb]], hb.schema)
    out = _run_both(scan, [SortSpec(_ref(0, T.STRING), True)])
    got = [s for s in out.to_pydict()["s"] if s is not None]
    assert got == sorted(s for s in strs if s is not None)
    _run_both(scan, [SortSpec(_ref(0, T.STRING), False)])


def test_sort_long_strings():
    # strings wider than one 7-byte word: exact (not truncated) ordering
    strs = ["x" * 20 + "a", "x" * 20 + "b", "x" * 20, "x" * 19 + "y",
            "x" * 30, "w" * 30]
    tbl = pa.table({"s": pa.array(strs)})
    from spark_rapids_tpu.columnar.batch import batch_from_arrow
    hb = batch_from_arrow(tbl)
    scan = CpuInMemoryScanExec([[hb]], hb.schema)
    out = _run_both(scan, [SortSpec(_ref(0, T.STRING), True)])
    assert out.to_pydict()["s"] == sorted(strs)


def test_sort_bool_and_dates():
    tbl = pa.table({
        "b": pa.array([True, False, None, True, False]),
        "d": pa.array([18000, 17000, 19000, None, 16000], type=pa.date32()),
    })
    from spark_rapids_tpu.columnar.batch import batch_from_arrow
    hb = batch_from_arrow(tbl)
    scan = CpuInMemoryScanExec([[hb]], hb.schema)
    _run_both(scan, [SortSpec(_ref(0, T.BOOLEAN), True),
                     SortSpec(_ref(1, T.DATE), False)])


def test_sort_by_expression(rng):
    from spark_rapids_tpu.expressions.arithmetic import Multiply
    vals = rng.integers(-100, 100, 1000)
    scan = _scan({"a": vals})
    expr = Multiply(_ref(0), lit(np.int64(-1)))
    _run_both(scan, [SortSpec(expr, True)])


def test_sort_empty_and_single():
    scan = _scan({"a": np.array([], dtype=np.int64)})
    _run_both(scan, [SortSpec(_ref(0), True)])
    scan = _scan({"a": np.array([7], dtype=np.int64)})
    out = _run_both(scan, [SortSpec(_ref(0), True)])
    assert out.to_pydict()["a"] == [7]


def test_cpu_sort_large_int64_with_nulls():
    # to_pandas float64 promotion would corrupt values above 2^53
    big = 2**53
    a = pa.array([big + 1, big, None, big + 3, big + 2], type=pa.int64())
    tbl = pa.table({"a": a})
    from spark_rapids_tpu.columnar.batch import batch_from_arrow
    hb = batch_from_arrow(tbl)
    scan = CpuInMemoryScanExec([[hb]], hb.schema)
    out = _run_both(scan, [SortSpec(_ref(0), True)])
    assert out.to_pydict()["a"] == [None, big, big + 1, big + 2, big + 3]


# -- out-of-core sort: sorted runs + packed-key merge (GpuSortExec:633) ----

@pytest.fixture
def force_external_sort():
    from spark_rapids_tpu.exec import sort as S
    S.FORCE_OUT_OF_CORE_SORT = True
    yield S
    S.FORCE_OUT_OF_CORE_SORT = False


def _multi_batch_scan(rng, n=9000, batches=5, with_strings=True):
    """One partition fed by several batches -> several sorted runs."""
    per = n // batches
    out = []
    for i in range(batches):
        d = {"a": rng.integers(-500, 500, per),
             "f": np.where(rng.random(per) < 0.05, np.nan,
                           rng.normal(size=per))}
        if with_strings:
            words = np.array(["", "a", "ab", "zz", "alpha", "Beta", "ζeta"])
            d["s"] = words[rng.integers(0, len(words), per)]
        out.append(batch_from_pydict(d))
    return CpuInMemoryScanExec([out], out[0].schema)


def test_external_sort_matches_oracle(rng, force_external_sort):
    S = force_external_sort
    before = S.EXTERNAL_SORT_EVENTS
    scan = _multi_batch_scan(rng)
    _run_both(scan, [SortSpec(_ref(0), True)])
    assert S.EXTERNAL_SORT_EVENTS > before, "external path did not engage"


def test_external_sort_multikey_strings_floats(rng, force_external_sort):
    scan = _multi_batch_scan(rng)
    _run_both(scan, [SortSpec(_ref(2, T.STRING), True),
                     SortSpec(_ref(1, T.DOUBLE), False)])
    _run_both(scan, [SortSpec(_ref(1, T.DOUBLE), True, nulls_first=False),
                     SortSpec(_ref(0), False)])


def test_external_sort_stability(force_external_sort):
    """Equal keys keep input order across run boundaries (stable merge)."""
    b1 = batch_from_pydict({"k": np.array([1, 1, 2]),
                            "tag": np.array([10, 11, 12])})
    b2 = batch_from_pydict({"k": np.array([1, 2, 2]),
                            "tag": np.array([20, 21, 22])})
    scan = CpuInMemoryScanExec([[b1, b2]], b1.schema)
    out = _run_both(scan, [SortSpec(_ref(0), True)])
    assert out.to_pydict()["tag"] == [10, 11, 20, 12, 21, 22]


def test_sort_split_oom_injection_falls_back(rng):
    """A SplitAndRetryOOM in the fast-path attempt (deterministically the
    first tracked point after the per-batch spill registrations) must
    push the sort to the external path, still matching the oracle."""
    from spark_rapids_tpu.exec import sort as S
    from spark_rapids_tpu.memory import retry as R
    scan = _multi_batch_scan(rng, n=4000, batches=4, with_strings=False)
    specs = [SortSpec(_ref(0), True)]
    cpu = CpuSortExec(specs, scan).collect_host()
    before = S.EXTERNAL_SORT_EVENTS
    # 4 child batches -> 4 from_device catalog adds before the attempt
    from spark_rapids_tpu.config import default_conf
    from spark_rapids_tpu.plan.overrides import insert_transitions
    plan = insert_transitions(TpuSortExec(specs, scan), default_conf())
    R.force_split_and_retry_oom(1, skip=4)
    try:
        tpu = plan.collect_host()
    finally:
        R.force_split_and_retry_oom(0)
    assert S.EXTERNAL_SORT_EVENTS > before, "fallback did not engage"
    assert_batches_equal(cpu, tpu, check_order=True)
