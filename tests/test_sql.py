"""SQL front-end tests: parse -> analyze -> differential CPU-vs-TPU.

Mirrors the reference's qa_nightly_select_test.py pattern (SQL corpus run
on both engines, rows compared) at unit scale.
"""

import numpy as np
import pytest

from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.session import TpuSession

from tests.asserts import assert_tpu_and_cpu_are_equal_collect


def _register(s: TpuSession, parts=2):
    rng = np.random.default_rng(11)
    n = 400
    t = {
        "k": rng.integers(0, 20, n).astype(np.int64),
        "v": np.round(rng.standard_normal(n), 3),
        "w": rng.integers(-50, 50, n).astype(np.int32),
        "s": np.array([f"str{i % 7}" for i in range(n)], dtype=object),
    }
    u = {
        "k": rng.integers(0, 25, 60).astype(np.int64),
        "cat": np.array([f"cat{i % 3}" for i in range(60)], dtype=object),
        "boost": rng.integers(1, 5, 60).astype(np.int64),
    }
    dates = {
        "d_sk": np.arange(100, dtype=np.int64),
        "d_date": np.array(np.datetime64("2000-01-01") +
                           np.arange(100), dtype="datetime64[D]"),
        "d_year": (2000 + (np.arange(100) // 40)).astype(np.int32),
    }
    s.create_or_replace_temp_view("t", s.create_dataframe(t, num_partitions=parts))
    s.create_or_replace_temp_view("u", s.create_dataframe(u))
    s.create_or_replace_temp_view("dates", s.create_dataframe(dates))
    return s


def both(sql, sort=True):
    def fn(session):
        _register(session)
        return session.sql(sql)
    assert_tpu_and_cpu_are_equal_collect(
        fn, ignore_order=sort,
        conf={"spark.rapids.sql.test.enabled": "false"})
    from tests.asserts import cpu_session
    s = _register(cpu_session())
    return s.sql(sql).collect()


def test_simple_select_where():
    rows = both("select k, v from t where w > 0 and k < 10")
    assert rows


def test_expressions_and_aliases():
    both("select k + 1 as k1, v * 2 v2, -w as nw, "
         "case when w > 0 then 'pos' when w < 0 then 'neg' else 'zero' end"
         " as sign from t")


def test_agg_group_having_order_limit():
    rows = both("select k, sum(v) as sv, count(*) as c, avg(v) av "
                "from t where w <> 0 group by k having count(*) > 2 "
                "order by sv desc limit 5", sort=False)
    assert len(rows) <= 5


def test_global_agg_no_group():
    rows = both("select count(*) as c, sum(v) s, min(w) mn, max(w) mx "
                "from t")
    assert len(rows) == 1


def test_join_on_condition():
    both("select t.k, t.v, u.cat from t join u on t.k = u.k "
         "where u.boost > 1")


def test_left_join_and_using():
    both("select t.k, u.cat from t left join u using (k)")


def test_comma_join_graph_with_pushdown():
    both("select t.k, sum(t.v * u.boost) sv from t, u, dates "
         "where t.k = u.k and t.w = dates.d_sk and dates.d_year = 2000 "
         "group by t.k")


def test_subquery_in_from():
    both("select x.k2, count(*) c from "
         "(select k + 1 as k2, v from t where v > 0) x group by x.k2")


def test_cte():
    both("with big as (select k, sum(v) sv from t group by k) "
         "select b1.k, b1.sv from big b1 where b1.sv > 0")


def test_uncorrelated_scalar_subquery():
    both("select k, v from t where v > (select avg(v) from t)")


def test_correlated_scalar_subquery_decorrelation():
    # the q1 pattern: per-key average compared against each row
    both("with ctr as (select k, w, sum(v) tot from t group by k, w) "
         "select c1.k, c1.tot from ctr c1 where c1.tot > "
         "(select avg(c2.tot) * 1.2 from ctr c2 where c2.k = c1.k)")


def test_exists_semi_join():
    both("select k, v from t where exists "
         "(select 1 from u where u.k = t.k and u.boost > 2)")


def test_not_exists_anti_join():
    both("select k from t where not exists "
         "(select 1 from u where u.k = t.k)")


def test_in_subquery():
    both("select k, w from t where k in (select k from u where boost >= 3)")


def test_not_in_subquery():
    both("select k from t where k not in (select k from u)")


def test_or_of_exists_existence_join():
    both("select k from t where w > 0 and (exists "
         "(select 1 from u where u.k = t.k and u.boost > 3) or exists "
         "(select 1 from u where u.k = t.k and u.cat = 'cat0'))")


def test_union_all_and_distinct():
    both("select k from t where w > 10 union all select k from u")
    both("select k from t where w > 10 union select k from u")


def test_intersect_except():
    both("select k from t intersect select k from u")
    both("select k from t except select k from u")


def test_distinct_and_in_list():
    both("select distinct k from t where k in (1, 2, 3, 5, 8)")


def test_between_like_null():
    both("select k, s from t where k between 3 and 12 and s like 'str%' "
         "and v is not null")


def test_order_by_ordinal_and_nulls():
    both("select k, sum(v) sv from t group by k order by 2 desc, 1",
         sort=False)


def test_date_arithmetic():
    both("select d_sk from dates where d_date between "
         "cast('2000-01-10' as date) and "
         "(cast('2000-01-10' as date) + interval 30 day)")


def test_substr_concat():
    both("select substr(s, 1, 4) p, s || '_x' cx, upper(s) us from t "
         "where length(s) > 3")


def test_window_function():
    both("select k, v, row_number() over "
         "(partition by k order by v desc) rn from t where w > 25")


def test_rollup():
    both("select k, w % 2, sum(v) sv from t where w > 40 "
         "group by rollup(k, w % 2)")


def test_cast_types():
    both("select cast(k as int) ki, cast(v as string) vs, "
         "cast(w as double) wd from t where k < 5")


def test_select_without_from():
    rows = both("select 1 + 2 as x, 'hi' as y")
    assert rows == [{"x": 3, "y": "hi"}]


def test_count_distinct_supported_others_clear_error():
    from tests.asserts import cpu_session
    s = _register(cpu_session())
    rows = s.sql("select count(distinct k) as c from t").collect()
    assert rows and rows[0]["c"] >= 1
    with pytest.raises(Exception, match="DISTINCT"):
        s.sql("select sum(distinct k) from t").collect()


def test_count_distinct_in_window_rejected():
    from tests.asserts import cpu_session
    s = _register(cpu_session())
    with pytest.raises(Exception, match="DISTINCT"):
        s.sql("select count(distinct v) over (partition by k) from t") \
            .collect()
