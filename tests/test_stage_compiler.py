"""StageCompiler tier-1 tests (exec/stage_compiler + plan/stages):

- the shared executable cache's hit/miss/evict/trace accounting;
- bounded-LRU eviction;
- zero new traces on the second run of an identical query (the
  ROADMAP-item-1 acceptance assertion);
- literal promotion: one compiled program across differing literals,
  bit-identical results, correct non-promotion of unsafe positions;
- stage fusion on/off bit-identity across TPC-DS tier-1 queries;
- persistent-cache conf wiring, async compile mode, stageCompile
  events, Prometheus counters and AutoTuner rule 7.
"""

import json

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.exec import stage_compiler as SC
from spark_rapids_tpu.expressions.base import Alias, col, lit

from tests.asserts import (assert_tpu_and_cpu_are_equal_collect,
                           cpu_session, tpu_session)

RNG = np.random.default_rng(11)


def _data(n=4000, seed=11):
    rng = np.random.default_rng(seed)
    return {"k": rng.integers(0, 50, n).astype(np.int64),
            "w": rng.integers(-100, 100, n).astype(np.int32),
            "v": rng.standard_normal(n)}


def _filter_agg(df, threshold):
    return (df.filter(col("w") > lit(threshold))
            .select(Alias(col("k") + lit(1), "k1"),
                    Alias(col("v"), "v"))
            .agg(F.sum("k1").alias("sk"), F.sum("v").alias("sv")))


# ---------------------------------------------------------------------------
# shared helper semantics
# ---------------------------------------------------------------------------

def test_get_or_build_hit_miss_trace_counters():
    import jax.numpy as jnp
    SC.reset_stats()

    def build():
        def run(x):
            return x + 1
        return run

    key = ("unit", "counters", 1)
    p1 = SC.get_or_build("test.unit", key, build)
    out = p1(jnp.arange(4))
    assert list(np.asarray(out)) == [1, 2, 3, 4]
    p2 = SC.get_or_build("test.unit", key, build)
    assert p2 is p1
    st = SC.stats()
    assert st["misses"] >= 1 and st["hits"] >= 1
    # exactly one trace for one signature, however often it is called
    p1(jnp.arange(4))
    assert SC.stats()["traces_by_kind"]["test.unit"] == 1
    # first dispatch was measured and counted as a compile
    assert st["compiles"] >= 1 and st["compile_s"] >= 0.0


def test_trace_counter_counts_signature_variants():
    import jax.numpy as jnp
    SC.reset_stats()

    def build():
        def run(x):
            return x * 2
        return run

    p = SC.get_or_build("test.variant", ("unit", "variants"), build)
    p(jnp.arange(8))
    p(jnp.arange(8).astype(np.float64))   # new dtype -> genuine retrace
    assert SC.stats()["traces_by_kind"]["test.variant"] == 2


def test_lru_eviction_bounded():
    import jax.numpy as jnp
    SC.clear()
    SC.reset_stats()
    old = SC.stats()["max_programs"]
    try:
        SC.set_max_programs(2)

        def build():
            def run(x):
                return x - 1
            return run

        for i in range(4):
            SC.get_or_build("test.evict", ("unit", "evict", i), build)
        st = SC.stats()
        assert st["programs"] <= 2
        assert st["evictions"] >= 2
        # evicted key rebuilds (miss), resident key hits
        SC.get_or_build("test.evict", ("unit", "evict", 3), build)
        assert SC.stats()["hits"] >= 1
        before = SC.stats()["misses"]
        SC.get_or_build("test.evict", ("unit", "evict", 0), build)
        assert SC.stats()["misses"] == before + 1
    finally:
        SC.set_max_programs(old)


# ---------------------------------------------------------------------------
# zero-retrace steady state (ROADMAP item 1 acceptance)
# ---------------------------------------------------------------------------

def test_second_run_of_identical_query_traces_nothing():
    s = tpu_session()
    df = s.create_dataframe(_data(), num_partitions=2)
    first = _filter_agg(df, 0).collect()
    SC.reset_stats()
    second = _filter_agg(df, 0).collect()
    st = SC.stats()
    assert st["traces"] == 0, \
        f"second identical run retraced: {st['traces_by_kind']}"
    assert st["misses"] == 0 and st["hits"] > 0
    assert first == second


def test_second_run_tpcds_query_traces_nothing():
    from spark_rapids_tpu.testing.tpcds import register_tables
    from spark_rapids_tpu.testing.tpcds_queries import QUERIES
    s = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    register_tables(s, sf=0.02)
    first = s.sql(QUERIES["q3"]).collect()
    SC.reset_stats()
    second = s.sql(QUERIES["q3"]).collect()
    st = SC.stats()
    assert st["traces"] == 0, \
        f"q3 second run retraced: {st['traces_by_kind']}"
    assert sorted(map(str, first)) == sorted(map(str, second))


# ---------------------------------------------------------------------------
# literal promotion
# ---------------------------------------------------------------------------

def test_promotion_unit_placeholders_and_slots():
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.expressions.base import BoundReference, Literal
    from spark_rapids_tpu.expressions.predicates import GreaterThan
    from spark_rapids_tpu.expressions.arithmetic import Add
    from spark_rapids_tpu.plan.stages import (PromotedLiteral,
                                              promote_stage_literals)
    w = BoundReference(0, T.INT, True, "w")
    v = BoundReference(1, T.DOUBLE, True, "v")
    ops = [("filter", GreaterThan(w, Literal(5, T.INT))),
           ("project", [Add(v, Literal(1.5, T.DOUBLE)),
                        # dtype mismatch (INT col vs LONG literal): kept
                        GreaterThan(w, Literal(7, T.LONG)),
                        # strings never promote
                        BoundReference(2, T.STRING, True, "s")])]
    new_ops, promoted = promote_stage_literals(ops)
    assert len(promoted) == 2
    assert [p.value for p in promoted] == [5, 1.5]
    assert "$lit0" in new_ops[0][1].sql()
    assert "$lit1" in new_ops[1][1][0].sql()
    assert "7" in new_ops[1][1][1].sql()          # mismatch: untouched
    assert isinstance(promoted[0], PromotedLiteral)
    # original tree untouched (plans are shared)
    assert "5" in ops[0][1].sql()


def test_promoted_literals_share_one_program_across_values():
    s = tpu_session()
    df = s.create_dataframe(_data(), num_partitions=1)
    r0 = _filter_agg(df, 0).collect()      # compiles the stage
    SC.reset_stats()
    r5 = _filter_agg(df, 5).collect()      # same shape, new literal
    st = SC.stats()
    assert st["traces"] == 0, \
        f"literal change recompiled: {st['traces_by_kind']}"
    assert r0 != r5                        # and the VALUES actually bind
    # oracle: both thresholds match the CPU engine bit-for-bit
    for thr, rows in ((0, r0), (5, r5)):
        c = _filter_agg(cpu_session().create_dataframe(
            _data(), num_partitions=1), thr).collect()
        assert abs(c[0]["sk"] - rows[0]["sk"]) == 0
        assert abs(c[0]["sv"] - rows[0]["sv"]) <= 1e-9 * abs(c[0]["sv"])


def test_promotion_disabled_still_correct():
    def fn(session):
        df = session.create_dataframe(_data(), num_partitions=2)
        return _filter_agg(df, 3)
    assert_tpu_and_cpu_are_equal_collect(
        fn, conf={"spark.rapids.sql.compile.literalPromotion": "false"})


def test_promoted_date_literals(tmp_path):
    import datetime
    rng = np.random.default_rng(3)
    days = rng.integers(10_000, 11_000, 1000)
    data = {"d": [datetime.date(1970, 1, 1) + datetime.timedelta(days=int(x))
                  for x in days],
            "x": rng.integers(0, 9, 1000).astype(np.int64)}

    def fn(session):
        df = session.create_dataframe(data, num_partitions=1)
        return (df.filter(col("d") >= lit(datetime.date(1998, 1, 1)))
                  .agg(F.sum("x").alias("sx"), F.count("x").alias("cx")))
    assert_tpu_and_cpu_are_equal_collect(fn)


def test_promoted_literal_inside_lambda_body():
    """Regression: a promoted literal inside a higher-order function's
    lambda body must bind through the lambda's derived EvalContext.  The
    compiled program is cached under a value-independent key, so a
    dropped literal_args binding would bake the FIRST query's constant
    into a program the second query shares — silent wrong results."""
    from spark_rapids_tpu import types as T
    rng = np.random.default_rng(7)
    data = {"a": [[int(v) for v in rng.integers(-9, 9, 1 + i % 4)]
                  for i in range(500)],
            "k": np.arange(500, dtype=np.int64)}
    schema = T.StructType([T.StructField("a", T.ArrayType(T.LONG)),
                           T.StructField("k", T.LONG)])

    def fn(mult):
        def run(session):
            df = session.create_dataframe(data, schema=schema,
                                          num_partitions=1)
            return (df.filter(col("k") >= lit(np.int64(0)))
                      .select(Alias(F.transform(
                          col("a"), lambda x: x * lit(np.int64(mult))),
                          "t"),
                          Alias(col("k"), "k")))
        return run

    # same plan shape, different lambda literal: the second query hits
    # the first's cached program and must still multiply by ITS value
    assert_tpu_and_cpu_are_equal_collect(fn(2))
    assert_tpu_and_cpu_are_equal_collect(fn(3))


def test_literal_vs_literal_comparison_not_promoted():
    """Regression: pure-constant subtrees (lit op lit) must NOT have
    their literals promoted to traced runtime args — the scalar-scalar
    eval branches run python-level ops (bool()/np.asarray()) that crash
    on a tracer.  Constant math stays baked into the program."""
    def fn(session):
        df = session.create_dataframe(_data(), num_partitions=1)
        return (df.filter(col("w") > lit(np.int32(5)) - lit(np.int32(2)))
                  .agg(F.sum("v").alias("sv"), F.count("w").alias("cw")))
    assert_tpu_and_cpu_are_equal_collect(fn)


# ---------------------------------------------------------------------------
# stage fusion on/off bit-identity over TPC-DS
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("qname", [
    "q3",
    # q3 stays in the smoke tier (cheap, covers filter+join+agg fusion);
    # the wider sweep is slow-only — fusion is default-on, so every
    # tier-1 TPC-DS vs-CPU test already executes through the compiler
    pytest.param("q1", marks=pytest.mark.slow),
    pytest.param("q7", marks=pytest.mark.slow),
    pytest.param("q15", marks=pytest.mark.slow),
    pytest.param("q19", marks=pytest.mark.slow),
])
def test_tpcds_fused_vs_per_operator_bit_identical(qname):
    """The stage compiler must be invisible to results: the same TPC-DS
    query through fused stages and through per-operator dispatch returns
    identical row sets (each side is separately compared against the CPU
    engine by test_tpcds.py; this pins the fusion pass itself)."""
    from spark_rapids_tpu.testing.rowcompare import rows_equal
    from spark_rapids_tpu.testing.tpcds import register_tables
    from spark_rapids_tpu.testing.tpcds_queries import QUERIES

    def run(extra):
        conf = {"spark.rapids.sql.test.enabled": "false"}
        conf.update(extra)
        s = tpu_session(conf)
        register_tables(s, sf=0.02)
        return s.sql(QUERIES[qname]).collect()

    fused = run({})
    unfused = run({"spark.rapids.sql.compile.stageFusion.enabled":
                   "false"})
    diff = rows_equal(unfused, fused, check_order=False, approx_float=True)
    assert diff is None, diff


def test_fusion_disabled_drops_fused_nodes():
    from spark_rapids_tpu.plan.overrides import TpuOverrides
    s = tpu_session({"spark.rapids.sql.compile.stageFusion.enabled":
                     "false"})
    df = s.create_dataframe(_data(), num_partitions=1)
    q = df.filter(col("w") > lit(0)).select(Alias(col("k") + lit(1), "k1"))
    plan = TpuOverrides(s.conf).apply(q._plan, for_explain=True)
    names = {n.name for n in plan.collect_nodes()}
    assert not any(n.startswith("TpuFused") for n in names), names


# ---------------------------------------------------------------------------
# cache-key correctness: schema / bucket changes compile separate programs
# ---------------------------------------------------------------------------

def test_different_schema_and_bucket_get_their_own_programs():
    s = tpu_session()
    df1 = s.create_dataframe(_data(1500, seed=1), num_partitions=1)
    _filter_agg(df1, 0).collect()
    SC.reset_stats()
    # different row bucket (forces new shapes end to end)
    df2 = s.create_dataframe(_data(700, seed=2), num_partitions=1)
    r2 = _filter_agg(df2, 0).collect()
    assert SC.stats()["misses"] > 0
    c = _filter_agg(cpu_session().create_dataframe(
        _data(700, seed=2), num_partitions=1), 0).collect()
    assert abs(c[0]["sk"] - r2[0]["sk"]) == 0


# ---------------------------------------------------------------------------
# tier 2 (persistent disk cache) + async compile
# ---------------------------------------------------------------------------

def test_persistent_cache_dir_conf(tmp_path):
    d = str(tmp_path / "xla-cache")
    s = tpu_session({"spark.rapids.sql.compile.cacheDir": d})
    try:
        df = s.create_dataframe(_data(800, seed=5), num_partitions=1)
        _filter_agg(df, 1).collect()
        st = SC.stats()
        assert st["disk_cache_dir"] == d
        assert st["disk_cache_error"] is None
    finally:
        SC.set_persistent_cache_dir("")
    assert SC.stats()["disk_cache_dir"] is None


def test_async_compile_bit_identical_and_warms():
    SC.clear()     # force fresh programs so the warm path actually runs

    def fn(session):
        # filter+select WITHOUT an aggregate: fuses to TpuFusedStageExec,
        # the exec that runs the async look-ahead
        df = session.create_dataframe(_data(2000, seed=7),
                                      num_partitions=2)
        return (df.filter(col("w") > lit(-5))
                  .select(Alias(col("k") * lit(3), "k3")))
    SC.reset_stats()
    assert_tpu_and_cpu_are_equal_collect(
        fn, conf={"spark.rapids.sql.compile.async": "true"})
    assert SC.stats()["async_compiles"] >= 1
    # the flag is session-scoped: the next default-conf action resets it
    s = tpu_session()
    s.create_dataframe({"z": np.arange(8)}, num_partitions=1).collect()
    assert SC.ASYNC_COMPILE is False


# ---------------------------------------------------------------------------
# observability: events, Prometheus, AutoTuner rule 7
# ---------------------------------------------------------------------------

def test_stage_compile_events_logged(tmp_path):
    log = tmp_path / "ev.jsonl"
    s = tpu_session({"spark.rapids.sql.eventLog.path": str(log)})
    # a unique row count -> unique bucket-independent shape is not
    # guaranteed, so force novelty through a fresh column layout
    rng = np.random.default_rng(17)
    df = s.create_dataframe(
        {"a1": rng.integers(0, 5, 900).astype(np.int16),
         "b1": rng.standard_normal(900).astype(np.float32)},
        num_partitions=1)
    (df.filter(col("a1") > lit(np.int16(1)))
       .agg(F.count("b1").alias("c"))).collect()
    evs = [json.loads(l) for l in log.read_text().splitlines()
           if '"stageCompile"' in l]
    assert evs, "no stageCompile events reached the event log"
    for e in evs:
        assert e["event"] == "stageCompile"
        assert e["duration_s"] >= 0.0
        assert e["tier"] in ("jit", "aot")
        assert e["stage_kind"]


def test_render_prometheus_stage_counters():
    from spark_rapids_tpu.aux.events import render_prometheus
    text = render_prometheus()
    for name in ("spark_rapids_tpu_stage_programs",
                 "spark_rapids_tpu_stage_cache_hits_total",
                 "spark_rapids_tpu_stage_cache_misses_total",
                 "spark_rapids_tpu_stage_cache_evictions_total",
                 "spark_rapids_tpu_stage_traces_total",
                 "spark_rapids_tpu_stage_compile_seconds_total"):
        assert name in text


def test_profile_compile_bucket(tmp_path):
    from spark_rapids_tpu.tools.profile import attribute
    from spark_rapids_tpu.tools.reader import load_profiles
    log = tmp_path / "prof.jsonl"
    lines = [
        json.dumps({"event": "queryStart", "query_id": 3, "span_id": 1,
                    "ts": 1.0, "v": 2, "description": "q", "conf": {}}),
        json.dumps({"event": "stageCompile", "query_id": 3, "span_id": 2,
                    "ts": 1.5, "v": 2, "stage_kind": "fused.stage",
                    "key": "abc", "duration_s": 2.0, "tier": "jit",
                    "disk_cache": False}),
        json.dumps({"event": "queryEnd", "query_id": 3, "span_id": 1,
                    "ts": 5.0, "v": 2, "duration_s": 4.0,
                    "semaphore_wait_s": 0.0, "events_dropped": 0}),
    ]
    log.write_text("\n".join(lines) + "\n")
    profiles, _ = load_profiles(str(log))
    att = attribute(profiles[0])
    assert att.raw["compile"] == 2.0
    assert att.scaled["compile"] > 0.0


def test_autotune_cold_compile_rule(tmp_path):
    from spark_rapids_tpu.tools.autotune import autotune_query
    from spark_rapids_tpu.tools.reader import load_profiles
    log = tmp_path / "cold.jsonl"
    lines = [json.dumps({"event": "queryStart", "query_id": 9,
                         "span_id": 1, "ts": 0.0, "v": 2,
                         "description": "cold", "conf": {}})]
    for i in range(9):
        lines.append(json.dumps(
            {"event": "stageCompile", "query_id": 9, "span_id": 2 + i,
             "ts": 0.1 * i, "v": 2, "stage_kind": f"fused.k{i}",
             "key": f"h{i}", "duration_s": 0.5, "tier": "jit",
             "disk_cache": False}))
    lines.append(json.dumps({"event": "queryEnd", "query_id": 9,
                             "span_id": 1, "ts": 6.0, "v": 2,
                             "duration_s": 6.0, "semaphore_wait_s": 0.0,
                             "events_dropped": 0}))
    log.write_text("\n".join(lines) + "\n")
    profiles, _ = load_profiles(str(log))
    recs = autotune_query(profiles[0])
    by_key = {r.key: r for r in recs}
    rec = by_key.get("spark.rapids.sql.compile.cacheDir")
    assert rec is not None, [r.key for r in recs]
    assert rec.evidence and any("stageCompile" in e for e in rec.evidence)
    # with the disk tier already on, the same events keep the rule silent
    warm = [json.loads(l) for l in lines]
    for e in warm:
        if e["event"] == "stageCompile":
            e["disk_cache"] = True
    warm_log = tmp_path / "warm.jsonl"
    warm_log.write_text("\n".join(json.dumps(e) for e in warm) + "\n")
    warm_recs = autotune_query(load_profiles(str(warm_log))[0][0])
    assert "spark.rapids.sql.compile.cacheDir" not in \
        {r.key for r in warm_recs}


# ---------------------------------------------------------------------------
# conf validation
# ---------------------------------------------------------------------------

def test_compile_conf_validation():
    from spark_rapids_tpu.config import TpuConf
    with pytest.raises(ValueError):
        TpuConf({"spark.rapids.sql.compile.maxPrograms": "0"})
    with pytest.raises(ValueError):
        TpuConf({"spark.rapids.sql.compile.async": "maybe"})
    c = TpuConf({"spark.rapids.sql.compile.maxPrograms": "64",
                 "spark.rapids.sql.compile.cacheDir": "/tmp/x",
                 "spark.rapids.sql.compile.async": "true",
                 "spark.rapids.sql.compile.literalPromotion": "false",
                 "spark.rapids.sql.compile.stageFusion.enabled": "false"})
    assert c.get("spark.rapids.sql.compile.maxPrograms") == 64
