"""Volume string function tests (reference: string_test.py)."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.expressions.base import Alias, col, lit

from tests.asserts import assert_tpu_and_cpu_are_equal_collect, cpu_session

_STRS = [None, "", "a", "Hello world", "FOO bar Baz", "x" * 30,
         "one two  three", "AbCdEf", "  pad  ", "tail "]


def _df(s, parts=2):
    return s.create_dataframe({"s": _STRS, "n": list(range(10))},
                              num_partitions=parts)


def test_reverse_initcap_differential():
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).select(
            Alias(F.reverse(col("s")), "r"),
            Alias(F.initcap(col("s")), "ic")))
    rows = _df(cpu_session()).select(
        Alias(F.reverse(col("s")), "r"),
        Alias(F.initcap(col("s")), "ic")).collect()
    assert rows[3]["r"] == "dlrow olleH"
    assert rows[3]["ic"] == "Hello World"
    assert rows[4]["ic"] == "Foo Bar Baz"


def test_repeat_pad_differential():
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).select(
            Alias(F.repeat(col("s"), 3), "r3"),
            Alias(F.lpad(col("s"), 12, "*"), "lp"),
            Alias(F.rpad(col("s"), 12, "-"), "rp"),
            Alias(F.lpad(col("s"), 2), "trunc")))
    rows = _df(cpu_session()).select(
        Alias(F.lpad(col("s"), 6, "*"), "lp"),
        Alias(F.rpad(col("s"), 6, "-"), "rp")).collect()
    assert rows[2]["lp"] == "*****a" and rows[2]["rp"] == "a-----"
    assert rows[3]["lp"] == "Hello " and rows[3]["rp"] == "Hello "


def test_locate_translate_differential():
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).select(
            Alias(F.locate("o", col("s")), "lo"),
            Alias(F.locate("o", col("s"), 6), "lo6"),
            Alias(F.instr(col("s"), "wor"), "iw"),
            Alias(F.translate(col("s"), "lo", "LO"), "tr")))
    rows = _df(cpu_session()).select(
        Alias(F.locate("o", col("s")), "lo"),
        Alias(F.translate(col("s"), "lo", "LO"), "tr")).collect()
    assert rows[3]["lo"] == 5                       # Hell[o]
    assert rows[3]["tr"] == "HeLLO wOrLd"


def test_split_and_concat_ws():
    s = cpu_session()
    rows = (_df(s).select(
        Alias(F.split(col("s"), " "), "sp"),
        Alias(F.concat_ws("-", col("s"), lit("z")), "cw")).collect())
    assert rows[3]["sp"] == ["Hello", "world"]
    assert rows[6]["sp"] == ["one", "two", "", "three"]
    assert rows[9]["sp"] == ["tail"]               # trailing empty dropped
    assert rows[3]["cw"] == "Hello world-z"
    assert rows[0]["cw"] == "z"                    # null input skipped
    from tests.asserts import tpu_session
    s2 = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    rows2 = (_df(s2).select(
        Alias(F.split(col("s"), " "), "sp"),
        Alias(F.concat_ws("-", col("s"), lit("z")), "cw")).collect())
    assert rows2 == rows


def test_translate_with_deletion_falls_back():
    from tests.asserts import tpu_session
    s = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    df = _df(s).select(Alias(F.translate(col("s"), "lox", "L"), "t"))
    assert "host tier" in df.explain()
    rows = df.collect()
    assert rows[3]["t"] == "HeLL wrLd"             # o, x deleted
