"""Struct support via constructor decomposition (round 5).

Struct CONSTRUCTOR forms never need a device struct plane: field access
folds to the field expr, struct equality expands to field-wise null-safe
conjunctions, and struct grouping keys decompose into their field
columns.  These differential tests assert the struct group-by and
struct-key join run fully on device (test mode raises on any fallback).
Struct COLUMNS from sources stay host-tier (documented gap)."""

import numpy as np
import pytest

from tests.asserts import assert_tpu_and_cpu_are_equal_collect

DEVICE_STRICT = {"spark.rapids.sql.test.enabled": "true",
                 "spark.rapids.sql.test.allowedNonGpu":
                     "CpuInMemoryScanExec,CpuProjectExec"}


def _data(n=500):
    rng = np.random.default_rng(3)
    return {"a": rng.integers(0, 5, n),
            "b": rng.integers(0, 4, n),
            "c": rng.integers(0, 3, n),
            "v": rng.standard_normal(n)}


def _sql(query, conf=None, n_parts=2):
    def fn(session):
        df = session.create_dataframe(_data(), num_partitions=n_parts)
        session.create_or_replace_temp_view("t", df)
        session.create_or_replace_temp_view(
            "u", session.create_dataframe(
                {"a": np.arange(5), "b": np.arange(5) % 4,
                 "w": np.arange(5, dtype=np.float64)}, num_partitions=1))
        return session.sql(query)
    assert_tpu_and_cpu_are_equal_collect(
        fn, ignore_order=True, approx_float=True, conf=conf or {})


def test_struct_field_access_folds_to_device():
    _sql("select struct(a, b).col1 x, named_struct('p', a, 'q', v).q y "
         "from t", conf=DEVICE_STRICT)


def test_struct_groupby_key_on_device():
    """group by struct(a, b): decomposes into field keys; the aggregate
    runs on device with no fallback tag."""
    _sql("select struct(a, b).col1 ka, struct(a, b).col2 kb, sum(v) s "
         "from t group by struct(a, b) order by ka, kb",
         conf=DEVICE_STRICT)


def test_struct_key_join_on_device():
    """join ON struct equality: expands to null-safe field pairs and
    rides the device hash join."""
    _sql("select t.a, t.b, u.w from t join u "
         "on struct(t.a, t.b) = struct(u.a, u.b) order by t.a, t.b",
         conf=DEVICE_STRICT)


def test_struct_equality_null_safe_semantics():
    """Spark: struct(1, null) = struct(1, null) is TRUE (field-wise
    null-safe)."""
    def fn(session):
        import pyarrow as pa
        df = session.create_dataframe(
            {"x": pa.array([1, 1, 2, None]),
             "y": pa.array([None, None, 3, 4])})
        session.create_or_replace_temp_view("n", df)
        return session.sql(
            "select n1.x, count(*) c from n n1 join n n2 "
            "on struct(n1.x, n1.y) = struct(n2.x, n2.y) group by n1.x "
            "order by n1.x")
    assert_tpu_and_cpu_are_equal_collect(fn, ignore_order=True)


def test_struct_value_output_host_fallback_is_correct():
    """Selecting the struct VALUE itself stays host-tier but must still
    be correct end to end."""
    _sql("select struct(a, b) s, v from t order by v limit 5",
         conf={"spark.rapids.sql.test.enabled": "false"})
