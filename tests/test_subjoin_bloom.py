"""Sub-partition join + bloom filter tests (reference:
GpuSubPartitionHashJoin suites + BloomFilter JNI tests)."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.expressions.base import Alias, col, lit

from tests.asserts import (assert_tpu_and_cpu_are_equal_collect, cpu_session,
                           tpu_session)

RNG = np.random.default_rng(6)


def _join_data(n=4000):
    return ({"k": RNG.integers(0, 500, n).astype(np.int64),
             "v": RNG.standard_normal(n)},
            {"k": np.arange(0, 500, 2, dtype=np.int64),
             "name": [f"n{i}" for i in range(250)]})


def test_subpartition_join_matches_plain():
    """Forcing a tiny threshold routes through the bucket machinery; the
    result must be identical to the plain join."""
    left, right = _join_data()
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(left, num_partitions=3)
        .join(s.create_dataframe(right, num_partitions=3), on="k",
              how="inner"),
        ignore_order=True, approx_float=True,
        conf={"spark.rapids.sql.join.subPartitionThresholdBytes": "1",
              "spark.rapids.sql.join.numSubPartitions": "4"})


def test_subpartition_left_join_and_counts():
    left, right = _join_data(2000)
    for how in ("inner", "left"):
        base = None
        for thresh in ("1g", "1"):
            s = tpu_session({"spark.rapids.sql.test.enabled": "false",
                             "spark.rapids.sql.join."
                             "subPartitionThresholdBytes": thresh})
            df = (s.create_dataframe(left, num_partitions=2)
                  .join(s.create_dataframe(right, num_partitions=2),
                        on="k", how=how))
            got = sorted([tuple(sorted(r.items())) for r in df.collect()])
            if base is None:
                base = got
            else:
                assert got == base, (how, thresh)


def test_bloom_filter_no_false_negatives():
    from spark_rapids_tpu.expressions.bloom import BloomFilter
    s = cpu_session()
    keys = np.arange(0, 1000, 3, dtype=np.int64)
    small = s.create_dataframe({"k": keys})
    bf = BloomFilter.build(small, "k", num_bits=1 << 14)
    big = s.create_dataframe({"k": np.arange(2000, dtype=np.int64)})
    kept = big.filter(F.might_contain(bf, col("k"))).collect()
    got = {r["k"] for r in kept}
    assert set(keys.tolist()) <= got          # NO false negatives
    # false positives bounded (generous): kept ≉ everything
    assert len(got) < 1200
    assert 0.0 < bf.saturation < 0.5


def test_bloom_probe_device_differential():
    from spark_rapids_tpu.expressions.bloom import BloomFilter
    s = cpu_session()
    bf = BloomFilter.build(
        s.create_dataframe({"k": np.arange(50, dtype=np.int64)}), "k",
        num_bits=1 << 12)
    data = {"k": [1, 49, 60, None, 1000]}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s2: s2.create_dataframe(data)
        .select(col("k"), Alias(F.might_contain(bf, col("k")), "mc")))
    rows = (cpu_session().create_dataframe(data)
            .select(Alias(F.might_contain(bf, col("k")), "mc")).collect())
    assert rows[0]["mc"] is True and rows[1]["mc"] is True
    assert rows[3]["mc"] is None              # null propagates


def test_bloom_string_keys():
    from spark_rapids_tpu.expressions.bloom import BloomFilter
    s = cpu_session()
    bf = BloomFilter.build(
        s.create_dataframe({"s": [f"id-{i}" for i in range(100)]}), "s",
        num_bits=1 << 13)
    df = cpu_session().create_dataframe(
        {"s": ["id-5", "id-99", "nope", "id-100"]})
    rows = df.select(Alias(F.might_contain(bf, col("s")), "m")).collect()
    assert rows[0]["m"] is True and rows[1]["m"] is True
