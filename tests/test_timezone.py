"""Timezone DB + datetime rebase tests (reference: tests/.../timezone/
suites + date_time_test.py from_utc_timestamp cases)."""

import datetime

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import Alias, col
from spark_rapids_tpu.expressions.timezone_db import (
    FromUTCTimestamp, TimeZoneDB, ToUTCTimestamp,
    rebase_gregorian_to_julian_days, rebase_julian_to_gregorian_days,
    rebase_julian_to_gregorian_micros)

from tests.asserts import assert_tpu_and_cpu_are_equal_collect, cpu_session

UTC = datetime.timezone.utc
_US = 1_000_000


def _us(dt: datetime.datetime) -> int:
    return int(dt.timestamp() * _US)


def test_tz_tables_parse_and_convert_scalar():
    import zoneinfo
    for zone in ("America/Los_Angeles", "Europe/Berlin", "Asia/Kolkata",
                 "Australia/Sydney", "UTC"):
        zi = zoneinfo.ZoneInfo(zone)
        for dt in (datetime.datetime(2024, 7, 4, 12, 0, tzinfo=UTC),
                   datetime.datetime(2024, 1, 15, 3, 30, tzinfo=UTC),
                   datetime.datetime(1999, 12, 31, 23, 59, tzinfo=UTC),
                   datetime.datetime(2030, 6, 1, 0, 0, tzinfo=UTC)):
            want_off = zi.utcoffset(dt.astimezone(zi)).total_seconds()
            got = TimeZoneDB.utc_to_local_us(
                np.array([_us(dt)], dtype=np.int64), zone, np)[0]
            assert got == _us(dt) + int(want_off) * _US, (zone, dt)


def test_tz_local_to_utc_roundtrip_and_dst_edges():
    zone = "America/Los_Angeles"
    # normal times roundtrip exactly
    for dt in (datetime.datetime(2024, 7, 4, 12, 0, tzinfo=UTC),
               datetime.datetime(2024, 12, 25, 8, 0, tzinfo=UTC)):
        us = np.array([_us(dt)], dtype=np.int64)
        local = TimeZoneDB.utc_to_local_us(us, zone, np)
        back = TimeZoneDB.local_to_utc_us(local, zone, np)
        assert back[0] == us[0]
    # ambiguous local time (fall-back 2024-11-03 01:30): earlier offset
    # (PDT, UTC-7) wins, java.time semantics
    amb = int(datetime.datetime(2024, 11, 3, 1, 30).replace(
        tzinfo=UTC).timestamp() * _US)
    got = TimeZoneDB.local_to_utc_us(np.array([amb]), zone, np)[0]
    assert got == amb + 7 * 3600 * _US
    # non-existent local time (spring-forward 2024-03-10 02:30) shifts
    gap = int(datetime.datetime(2024, 3, 10, 2, 30).replace(
        tzinfo=UTC).timestamp() * _US)
    got2 = TimeZoneDB.local_to_utc_us(np.array([gap]), zone, np)[0]
    assert got2 == gap + 8 * 3600 * _US     # resolved with PST offset


def test_from_to_utc_timestamp_differential():
    base = datetime.datetime(2024, 3, 9, 12, 0, tzinfo=UTC)
    data = {"ts": [base + datetime.timedelta(hours=h) for h in range(48)]}

    def q(s):
        return (s.create_dataframe(data)
                .select(Alias(FromUTCTimestamp(col("ts"),
                                               "America/Los_Angeles"), "la"),
                        Alias(FromUTCTimestamp(col("ts"),
                                               "Asia/Kolkata"), "ist"),
                        Alias(ToUTCTimestamp(col("ts"),
                                             "Europe/Berlin"), "ber")))
    assert_tpu_and_cpu_are_equal_collect(q)
    rows = q(cpu_session()).collect()
    # ground truth via zoneinfo
    import zoneinfo
    la = zoneinfo.ZoneInfo("America/Los_Angeles")
    for i, h in enumerate(range(48)):
        ts = base + datetime.timedelta(hours=h)
        want = ts.astimezone(la).replace(tzinfo=UTC)
        assert rows[i]["la"] == want, (i, rows[i]["la"], want)


def test_unknown_zone_raises_at_plan_time():
    with pytest.raises(KeyError, match="Not/AZone"):
        FromUTCTimestamp(col("ts"), "Not/AZone")


def test_rebase_julian_gregorian_days():
    """Spark RebaseDateTime semantics: the CIVIL DATE is preserved — a
    legacy value displaying as julian 1582-10-04 becomes the proleptic
    gregorian day count of 1582-10-04 (hybrid -141428 -> -141438)."""
    import datetime as dt

    def greg_days(y, m, d):
        return (dt.date(y, m, d) - dt.date(1970, 1, 1)).days

    assert rebase_julian_to_gregorian_days(
        np.array([-141428]))[0] == greg_days(1582, 10, 4)
    # the day AFTER the switch is already gregorian: unchanged
    assert rebase_julian_to_gregorian_days(
        np.array([-141427]))[0] == greg_days(1582, 10, 15)
    # modern dates unchanged
    assert rebase_julian_to_gregorian_days(np.array([0, 19000])).tolist() \
        == [0, 19000]
    # roundtrip across centuries
    days = np.array([-141428, -200000, -300000, -500000, -700000])
    back = rebase_gregorian_to_julian_days(
        rebase_julian_to_gregorian_days(days))
    assert back.tolist() == days.tolist()


def test_rebase_matches_known_spark_values():
    """Drift widths per era: 10 days at the switch, 5 days around 1000 AD
    (julian 1000-01-01 == proleptic gregorian 1000-01-06 physically, so
    same-civil-date rebase moves the count by that drift)."""
    import datetime as dt

    def greg_days(y, m, d):
        return (dt.date(y, m, d) - dt.date(1970, 1, 1)).days

    # julian civil 1582-10-04 (hybrid -141428): count moves by -10
    assert rebase_julian_to_gregorian_days(np.array([-141428]))[0] \
        == -141428 - 10
    # julian civil 1000-01-01: physical day of greg 1000-01-06, rebased
    # count = greg_days(1000, 1, 1) -> drift of -5 days
    hybrid_1000 = greg_days(1000, 1, 6)   # physical == julian 1000-01-01
    assert rebase_julian_to_gregorian_days(
        np.array([hybrid_1000]))[0] == greg_days(1000, 1, 1)
    # micros variant preserves time-of-day
    us = np.array([-141428 * 86400 * _US + 12 * 3600 * _US])
    out = rebase_julian_to_gregorian_micros(us)[0]
    assert out == greg_days(1582, 10, 4) * 86400 * _US + 12 * 3600 * _US
