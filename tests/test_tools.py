"""Offline diagnostic toolkit tests: versioned event-log reader
(rotation / gzip / truncation / v1-v2), bottleneck attribution, the
profile/autotune/compare CLI, the live resource sampler, the hardened
JSONL sink, the event-kind catalog, and the Prometheus exposition
format (reference: spark-rapids-tools Qualification/Profiling +
AutoTuner over Spark event logs)."""

import gzip
import json
import os

import numpy as np
import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu import functions as F
from spark_rapids_tpu.aux import events as EV
from spark_rapids_tpu.aux import profiler as PROF
from spark_rapids_tpu.aux import sampler as SMP
from spark_rapids_tpu.expressions.base import Alias, col
from spark_rapids_tpu.tools import __main__ as CLI
from spark_rapids_tpu.tools.autotune import (autotune, autotune_query,
                                             render_recommendations,
                                             to_conf_dict)
from spark_rapids_tpu.tools.compare import compare, render_compare
from spark_rapids_tpu.tools.profile import attribute, render_report
from spark_rapids_tpu.tools.reader import load_profiles, read_events

from tests.asserts import tpu_session

RNG = np.random.default_rng(23)
_DATA = {"k": RNG.integers(0, 7, 20000),
         "v": RNG.standard_normal(20000)}


def _run_logged_query(log):
    s = tpu_session({"spark.rapids.sql.test.enabled": "false",
                     "spark.rapids.sql.eventLog.path": str(log)})
    df = s.create_dataframe(_DATA, num_partitions=2)
    out = df.group_by("k").agg(Alias(F.sum(col("v")), "sv")).collect()
    return s, out


def _jline(kind, query_id, span_id, ts, v=2, **payload):
    return json.dumps({"event": kind, "query_id": query_id,
                       "span_id": span_id, "ts": ts, "v": v, **payload})


# ---------------------------------------------------------------------------
# reader
# ---------------------------------------------------------------------------

def test_reader_roundtrip_tree_and_truncated_tail(tmp_path):
    log = tmp_path / "ev.jsonl"
    s, _ = _run_logged_query(log)
    # torn final line: the process died mid-write
    with open(log, "a") as f:
        f.write('{"event": "spill", "query_id": 1, "by')
    profiles, diag = load_profiles(str(log))
    assert diag.truncated_lines == 1
    assert diag.header_versions == [EV.EVENT_SCHEMA_VERSION]
    assert not diag.unknown_kinds
    assert len(profiles) == 1
    qp = profiles[0]
    assert qp.complete and qp.description == "collect"
    # v2 structure: a real tree (children), per-partition timelines
    spans = qp.exec_spans()
    assert spans, "span tree must reconstruct"
    assert any(sp.children for sp in spans), "tree must have edges"
    assert any(sp.partitions for sp in spans), \
        "partition timelines must survive the round trip"
    for sp in spans:
        for p in sp.partitions:
            assert p["end_s"] >= p["start_s"]
    # queryStart carried the session's non-default conf
    assert "spark.rapids.sql.eventLog.path" in qp.conf


def test_reader_v1_lines_load_flat(tmp_path):
    log = tmp_path / "v1.jsonl"
    lines = [
        _jline("queryStart", 9, 1, 1.0, v=1, description="old"),
        _jline("spanMetrics", 9, 2, 2.0, v=1, node="TpuProjectExec",
               opTime=0.5),
        _jline("spanMetrics", 9, 3, 2.0, v=1, node="TpuFilterExec",
               opTime=0.2),
        _jline("queryEnd", 9, 1, 3.0, v=1, duration_s=2.0),
    ]
    log.write_text("\n".join(lines) + "\n")
    profiles, diag = load_profiles(str(log))
    assert len(profiles) == 1
    qp = profiles[0]
    # no parent_id in v1: spans load as a flat root list, still rankable
    assert len(qp.roots) == 2
    att = attribute(qp)
    assert att.wall_s == 2.0
    assert att.scaled["compute"] > 0


def test_reader_splits_restarted_process_runs(tmp_path):
    """Query ids and monotonic clocks restart per process; two runs
    appending to one log must load as two profiles, not one merged
    corrupt timeline."""
    log = tmp_path / "two_runs.jsonl"

    def run(t0):
        return [
            _jline("queryStart", 1, 1, t0, description="r"),
            _jline("spanMetrics", 1, 2, t0 + 0.5, parent_id=1, depth=1,
                   node="TpuProjectExec", desc="p", opTime=0.4,
                   start_s=t0, end_s=t0 + 1.0),
            _jline("queryEnd", 1, 1, t0 + 1.0, duration_s=1.0),
        ]

    # second run's clock restarted BELOW the first's; run-1 samples sit
    # at timestamps that fall inside run-2's window on run-2's clock
    r1_samples = [_jline("resourceSample", -1, -1, 5.5, pool_used_bytes=9)]
    log.write_text("\n".join(run(100.0) + r1_samples + run(5.0)) + "\n")
    profiles, _ = load_profiles(str(log))
    assert len(profiles) == 2
    assert all(p.complete for p in profiles)
    for p in profiles:
        assert abs(attribute(p).wall_s - 1.0) < 1e-6
        assert len(p.spans) == 1
    # the run-1 sample (ts 5.5) must NOT attach to run-2's query
    # (window [5.0, 6.0] on a DIFFERENT clock)
    assert profiles[1].samples == []


def test_reader_rejects_future_schema(tmp_path):
    log = tmp_path / "future.jsonl"
    log.write_text(_jline("queryStart", 1, 1, 1.0, v=99) + "\n")
    with pytest.raises(ValueError, match="schema v99"):
        read_events(str(log))


def test_reader_missing_file(tmp_path):
    with pytest.raises(FileNotFoundError):
        read_events(str(tmp_path / "nope.jsonl"))


# ---------------------------------------------------------------------------
# sink hardening: rotation, compression, atexit flush
# ---------------------------------------------------------------------------

def test_sink_rotation_and_reader_walks_the_set(tmp_path):
    p = str(tmp_path / "rot.jsonl")
    sink = EV.JsonlEventLogSink(p, max_bytes=400, flush_every=2)
    for i in range(20):
        sink.emit(EV.Event("spill", 1, 2, float(i),
                           {"tier": "device->host", "bytes": i}))
    sink.close()
    rotated = [f for f in os.listdir(tmp_path) if f.startswith("rot.jsonl.")]
    assert rotated, "sink must rotate past maxBytes"
    # every file (fresh and rotated) leads with a schema header
    for name in rotated + ["rot.jsonl"]:
        first = json.loads(open(tmp_path / name).readline())
        assert first["event"] == "eventLogHeader"
        assert first["v"] == EV.EVENT_SCHEMA_VERSION
    events, diag = read_events(p)
    assert len(events) == 20, "reader must walk the whole rotated set"
    assert len(diag.files) == len(rotated) + 1
    assert [e.payload["bytes"] for e in events] == list(range(20))


def test_sink_gzip_compression_roundtrip(tmp_path):
    p = str(tmp_path / "ev.jsonl")
    sink = EV.JsonlEventLogSink(p, compress=True, flush_every=3)
    for i in range(10):
        sink.emit(EV.Event("oom", 4, 1, float(i), {"needed": i}))
    sink.close()
    with open(p, "rb") as f:
        assert f.read(2) == b"\x1f\x8b", "gzip magic expected"
    # multi-member stream decompresses as one concatenation
    text = gzip.decompress(open(p, "rb").read()).decode()
    assert text.count("\n") == 11    # header + 10 events
    events, diag = read_events(p)
    assert [e.payload["needed"] for e in events] == list(range(10))


def test_reader_tolerates_truncated_gzip_tail(tmp_path):
    """A process killed mid-write leaves a partial gzip member; the
    reader must count it as truncation, not crash."""
    p = str(tmp_path / "gz.jsonl")
    sink = EV.JsonlEventLogSink(p, compress=True, flush_every=2)
    for i in range(6):
        sink.emit(EV.Event("oom", 1, 1, float(i), {"needed": i}))
    sink.close()
    data = open(p, "rb").read()
    with open(p, "wb") as f:
        f.write(data[:-20])     # chop mid-member
    events, diag = read_events(p)
    assert diag.truncated_lines >= 1
    assert events, "the intact prefix must still load"
    assert all(e.kind == "oom" for e in events)


def test_sink_rotation_with_shared_path_writers(tmp_path):
    """Two sinks on one path (the sampler + per-query configuration):
    rotation must never lose events or rename a file out from under the
    sibling permanently — stale writers migrate at their next batch."""
    p = str(tmp_path / "shared.jsonl")
    a = EV.JsonlEventLogSink(p, max_bytes=600, flush_every=1)
    b = EV.JsonlEventLogSink(p, max_bytes=600, flush_every=1)
    for i in range(30):
        (a if i % 2 else b).emit(
            EV.Event("spill", 1, 1, float(i),
                     {"bytes": i, "tier": "device->host"}))
    a.close()
    b.close()
    events, _diag = read_events(p)
    assert sorted(e.payload["bytes"] for e in events) == list(range(30))


def test_sink_atexit_flush_preserves_tail(tmp_path):
    p = str(tmp_path / "tail.jsonl")
    sink = EV.JsonlEventLogSink(p)     # default batch of 64: stays pending
    sink.emit(EV.Event("spill", 1, 1, 0.5, {"bytes": 7,
                                            "tier": "device->host"}))
    assert sum(1 for _ in open(p)) == 1, "only the header is on disk yet"
    EV._flush_eventlog_sinks()          # what atexit runs
    lines = open(p).read().splitlines()
    assert len(lines) == 2
    assert json.loads(lines[1])["bytes"] == 7
    sink.close()


def test_eventlog_confs_validated_at_set_conf():
    s = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    with pytest.raises(ValueError):
        s.set_conf("spark.rapids.sql.eventLog.maxBytes", "-5")
    with pytest.raises(ValueError):
        s.set_conf("spark.rapids.sql.eventLog.compress", "maybe")
    s.set_conf("spark.rapids.sql.eventLog.maxBytes", "64m")
    assert s.conf.get(C.EVENT_LOG_MAX_BYTES.key) == 64 << 20


# ---------------------------------------------------------------------------
# attribution + profile report
# ---------------------------------------------------------------------------

def test_profile_bucket_total_within_5pct_of_wall(tmp_path):
    log = tmp_path / "ev.jsonl"
    _run_logged_query(log)
    profiles, diag = load_profiles(str(log))
    assert profiles
    for qp in profiles:
        att = attribute(qp)
        assert att.wall_s > 0
        assert abs(att.scaled_total() - att.wall_s) <= 0.05 * att.wall_s, \
            (att.scaled_total(), att.wall_s)
        assert all(v >= 0 for v in att.scaled.values())
    report = render_report(profiles, diag)
    assert "Wall-clock decomposition" in report
    assert "Top operators by exclusive time" in report
    assert "Partition timeline" in report
    assert "bottleneck=" in report


def test_profile_cli(tmp_path, capsys):
    log = tmp_path / "ev.jsonl"
    _run_logged_query(log)
    assert CLI.main(["profile", str(log)]) == 0
    out = capsys.readouterr().out
    assert "== Query " in out and "decomposition" in out
    assert CLI.main(["profile", str(log), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    q = payload["queries"][0]
    assert q["wall_s"] > 0 and q["bottleneck"]
    total = sum(q["buckets_scaled_s"].values())
    assert abs(total - q["wall_s"]) <= 0.05 * q["wall_s"]


def test_profile_report_flags_ring_drops(tmp_path):
    """Satellite contract: ring truncation is surfaced, never silent."""
    log = tmp_path / "drop.jsonl"
    lines = [
        _jline("queryStart", 3, 1, 1.0, description="q"),
        _jline("queryEnd", 3, 1, 2.0, duration_s=1.0, events_dropped=12),
    ]
    log.write_text("\n".join(lines) + "\n")
    profiles, diag = load_profiles(str(log))
    assert diag.dropped_events == 12
    report = render_report(profiles, diag)
    assert "dropped" in report and "lower bound" in report


def test_profile_report_flags_lock_order_violations(tmp_path):
    """A query whose log carries lockOrderViolation events (the runtime
    spark.rapids.debug.lockOrder validator) gets a !! line naming the
    backward edges; a clean query gets none."""
    log = tmp_path / "lock.jsonl"
    lines = [
        _jline("queryStart", 4, 1, 1.0, description="q"),
        _jline("lockOrderViolation", 4, 1, 1.5, held="arbiter",
               acquiring="catalog",
               order="spool<catalog<semaphore<arbiter"),
        _jline("queryEnd", 4, 1, 2.0, duration_s=1.0),
    ]
    log.write_text("\n".join(lines) + "\n")
    profiles, diag = load_profiles(str(log))
    report = render_report(profiles, diag)
    assert "1 lock-order violation(s)" in report
    assert "arbiter->catalog" in report
    clean = tmp_path / "clean.jsonl"
    clean.write_text("\n".join([
        _jline("queryStart", 5, 1, 1.0, description="q"),
        _jline("queryEnd", 5, 1, 2.0, duration_s=1.0)]) + "\n")
    profiles, diag = load_profiles(str(clean))
    assert "lock-order" not in render_report(profiles, diag)


# ---------------------------------------------------------------------------
# autotuner
# ---------------------------------------------------------------------------

def _stall_heavy_log(tmp_path):
    log = tmp_path / "stall.jsonl"
    lines = [
        _jline("queryStart", 7, 1, 10.0, description="stally",
               conf={"spark.rapids.pipeline.depth": 2}),
        _jline("pipelineSpool", 7, 2, 12.0, boundary="decode", batches=40,
               producer_busy_s=1.0, producer_stall_s=2.4,
               consumer_stall_s=0.05, peak_depth=2),
        _jline("pipelineSpool", 7, 3, 13.0, boundary="transfer", batches=40,
               producer_busy_s=0.8, producer_stall_s=1.1,
               consumer_stall_s=0.02, peak_depth=2),
        _jline("spanMetrics", 7, 4, 14.0, parent_id=1, depth=1,
               node="TpuHashAggregateExec", desc="agg", opTime=1.0,
               start_s=10.0, end_s=15.0),
        _jline("queryEnd", 7, 1, 15.0, duration_s=5.0,
               semaphore_wait_s=0.0, events_dropped=0),
    ]
    log.write_text("\n".join(lines) + "\n")
    return log


def test_autotune_producer_stall_rule(tmp_path):
    """Acceptance: at least one evidence-cited recommendation on a
    stall-heavy synthetic log."""
    log = _stall_heavy_log(tmp_path)
    profiles, _ = load_profiles(str(log))
    recs = autotune(profiles)
    assert recs, "stall-heavy log must produce a recommendation"
    by_key = {r.key: r for r in recs}
    depth = by_key["spark.rapids.pipeline.depth"]
    assert depth.current == 2 and depth.recommended == 4
    assert depth.evidence and any("pipelineSpool" in e
                                  for e in depth.evidence)
    assert "producer" in depth.reason
    conf = to_conf_dict(recs)
    assert conf["spark.rapids.pipeline.depth"] == "4"
    # the emitted dict is genuinely ready-to-apply
    C.TpuConf(dict(conf))
    text = render_recommendations(recs)
    assert "evidence:" in text and "Ready-to-apply conf" in text
    # at the depth cap the rule stays silent instead of emitting a no-op
    profiles[0].conf["spark.rapids.pipeline.depth"] = 16
    capped = autotune_query(profiles[0])
    assert "spark.rapids.pipeline.depth" not in {r.key for r in capped}


def test_autotune_fetch_retry_rule(tmp_path):
    log = tmp_path / "fetch.jsonl"
    lines = [
        _jline("queryStart", 8, 1, 1.0, description="retries"),
        *[_jline("fetchRetry", 8, 1, 1.0 + 0.1 * i, peer="w1",
                 shuffle_id=3, partition=i, attempt=1, wait_ms=300.0)
          for i in range(4)],
        _jline("queryEnd", 8, 1, 3.0, duration_s=2.0),
    ]
    log.write_text("\n".join(lines) + "\n")
    profiles, _ = load_profiles(str(log))
    recs = autotune_query(profiles[0])
    keys = {r.key for r in recs}
    assert "spark.rapids.shuffle.fetch.timeoutMs" in keys
    rec = next(r for r in recs
               if r.key == "spark.rapids.shuffle.fetch.timeoutMs")
    assert rec.current == 30_000 and rec.recommended == 60_000
    assert any("fetchRetry" in e for e in rec.evidence)


def test_autotune_spill_pressure_rule(tmp_path):
    log = tmp_path / "spill.jsonl"
    lines = [
        _jline("queryStart", 9, 1, 1.0, description="spilly",
               conf={"spark.rapids.sql.concurrentGpuTasks": 4}),
        *[_jline("spill", 9, 1, 1.1 + 0.1 * i, tier="device->host",
                 bytes=1 << 20, duration_s=0.2) for i in range(3)],
        _jline("splitRetry", 9, 1, 1.6, task_id=1, pieces=2),
        _jline("queryEnd", 9, 1, 3.0, duration_s=2.0),
    ]
    log.write_text("\n".join(lines) + "\n")
    recs = autotune_query(load_profiles(str(log))[0][0])
    by_key = {r.key: r for r in recs}
    assert by_key["spark.rapids.sql.concurrentGpuTasks"].recommended == 3
    assert by_key["spark.rapids.sql.batchSizeBytes"].recommended \
        == (512 << 20) // 2
    assert any("spill" in e for e in
               by_key["spark.rapids.sql.concurrentGpuTasks"].evidence)


def test_autotune_deadlock_break_rule(tmp_path):
    """Rule 6: repeated deadlock breaks / BUFN splits -> shed device
    concurrency, with the break events as evidence."""
    log = tmp_path / "deadlock.jsonl"
    lines = [
        _jline("queryStart", 11, 1, 1.0, description="contended",
               conf={"spark.rapids.sql.concurrentGpuTasks": 4}),
        _jline("deadlockBreak", 11, 1, 1.2, task_id=7, exc="RetryOOM",
               blocked_tasks=4, forced=False, wake_count=1),
        _jline("deadlockBreak", 11, 1, 1.4, task_id=7,
               exc="SplitAndRetryOOM", blocked_tasks=4, forced=False,
               wake_count=2),
        _jline("queryEnd", 11, 1, 3.0, duration_s=2.0),
    ]
    log.write_text("\n".join(lines) + "\n")
    recs = autotune_query(load_profiles(str(log))[0][0])
    by_key = {r.key: r for r in recs}
    rec = by_key["spark.rapids.sql.concurrentGpuTasks"]
    assert rec.current == 4 and rec.recommended == 3
    assert any("deadlockBreak" in e for e in rec.evidence)
    assert "BUFN split" in rec.reason
    # a single break stays silent: the mechanism doing its job once is
    # not evidence of chronic contention
    single = tmp_path / "one.jsonl"
    single.write_text("\n".join([
        _jline("queryStart", 12, 1, 1.0, description="once"),
        _jline("deadlockBreak", 12, 1, 1.2, task_id=3, exc="RetryOOM",
               blocked_tasks=2, forced=False, wake_count=1),
        _jline("queryEnd", 12, 1, 2.0, duration_s=1.0),
    ]) + "\n")
    assert "spark.rapids.sql.concurrentGpuTasks" not in {
        r.key for r in autotune_query(load_profiles(str(single))[0][0])}


def test_autotune_deadlock_breaks_at_serial_raise_pool_fraction(tmp_path):
    """Rule 6 at concurrentGpuTasks=1: nothing left to shed — recommend
    a bigger pool fraction instead."""
    log = tmp_path / "serial.jsonl"
    lines = [
        _jline("queryStart", 13, 1, 1.0, description="serial",
               conf={"spark.rapids.sql.concurrentGpuTasks": 1}),
        *[_jline("deadlockBreak", 13, 1, 1.0 + 0.1 * i, task_id=5,
                 exc="SplitAndRetryOOM", blocked_tasks=1, forced=False,
                 wake_count=i + 1) for i in range(3)],
        _jline("queryEnd", 13, 1, 3.0, duration_s=2.0),
    ]
    log.write_text("\n".join(lines) + "\n")
    recs = autotune_query(load_profiles(str(log))[0][0])
    by_key = {r.key: r for r in recs}
    rec = by_key["spark.rapids.memory.gpu.allocFraction"]
    assert rec.recommended == pytest.approx(0.9)
    conf = to_conf_dict([rec])
    C.TpuConf(dict(conf))       # genuinely ready-to-apply


def test_autotune_quiet_on_healthy_log(tmp_path):
    log = tmp_path / "ok.jsonl"
    lines = [
        _jline("queryStart", 2, 1, 1.0, description="fine"),
        _jline("spanMetrics", 2, 3, 1.8, parent_id=1, depth=1,
               node="TpuProjectExec", desc="p", opTime=0.9,
               start_s=1.0, end_s=2.0),
        _jline("queryEnd", 2, 1, 2.0, duration_s=1.0,
               semaphore_wait_s=0.01),
    ]
    log.write_text("\n".join(lines) + "\n")
    assert autotune(load_profiles(str(log))[0]) == []


def test_autotune_cli_json(tmp_path, capsys):
    log = _stall_heavy_log(tmp_path)
    assert CLI.main(["autotune", str(log), "--json"]) == 0
    conf = json.loads(capsys.readouterr().out)
    assert conf.get("spark.rapids.pipeline.depth") == "4"


# ---------------------------------------------------------------------------
# bench compare
# ---------------------------------------------------------------------------

def _bench_payload(value, overlap, geomean):
    return {"metric": "filter_project_hash_agg_rows_per_sec",
            "value": value, "unit": "rows/s", "vs_baseline": 2.0,
            "tpu_s": 1.0, "cpu_s": 2.0,
            "pipeline": {"overlap_ratio": overlap,
                         "consumer_stall_s": 0.5, "peak_depth": 2},
            "tpcds": {"geomean_speedup": geomean, "queries_counted": 10},
            "chaos": {"faults_injected": 0, "task_retries": 0}}


def test_compare_payloads_and_regression_flag(tmp_path):
    a = tmp_path / "BENCH_r01.json"
    b = tmp_path / "BENCH_r02.json"
    a.write_text(json.dumps(_bench_payload(1000, 0.8, 3.0)) + "\n")
    b.write_text(json.dumps(_bench_payload(500, 0.2, 3.2)) + "\n")
    out = compare([str(a), str(b)])
    assert out["files"] == ["BENCH_r01.json", "BENCH_r02.json"]
    rows = {r["metric"]: r for r in out["rows"]}
    assert rows["rows/s"]["values"] == [1000, 500]
    assert rows["rows/s"]["delta_pct"] == -50.0
    assert rows["rows/s"]["regression"] is True
    assert rows["TPC-DS geomean"]["regression"] is False
    text = render_compare([str(a), str(b)])
    assert "regressions" in text and "rows/s" in text


def test_compare_cli(tmp_path, capsys):
    a = tmp_path / "a.json"
    a.write_text(json.dumps(_bench_payload(10, 0.5, 1.0)) + "\n")
    assert CLI.main(["compare", str(a)]) == 0
    assert "BENCH comparison" in capsys.readouterr().out


def test_compare_takes_last_json_line(tmp_path):
    p = tmp_path / "multi.json"
    p.write_text("garbage\n"
                 + json.dumps({"value": 1}) + "\n"
                 + json.dumps({"value": 2}) + "\n")
    out = compare([str(p)])
    rows = {r["metric"]: r for r in out["rows"]}
    assert rows["rows/s"]["values"] == [2]


def test_compare_skips_and_flags_failed_payload(tmp_path):
    """The BENCH_r05 shape: a budget-exceeded run records value 0 — a
    healthy-vs-failed comparison must say 'run failed', never a
    −100%/÷0 regression (in either direction)."""
    good = tmp_path / "BENCH_r04.json"
    bad = tmp_path / "BENCH_r05.json"
    good.write_text(json.dumps(_bench_payload(1000, 0.8, 3.0)) + "\n")
    bad.write_text(json.dumps({
        "metric": "filter_project_hash_agg_rows_per_sec", "value": 0,
        "unit": "rows/s", "vs_baseline": 0.0,
        "error": "primary phase exceeded BENCH_BUDGET_S",
        "budget_exceeded": True}) + "\n")
    out = compare([str(good), str(bad)])
    assert "BENCH_r05.json" in out["failed"]
    assert "BENCH_BUDGET_S" in out["failed"]["BENCH_r05.json"]
    rows = {r["metric"]: r for r in out["rows"]}
    # the failed run's placeholder zeros never enter a row or a delta
    assert rows["rows/s"]["values"] == [1000, None]
    assert rows["rows/s"]["delta_pct"] == 0.0
    assert not any(r.get("regression") for r in out["rows"])
    text = render_compare([str(good), str(bad)])
    assert "run failed" in text and "regressions" not in text
    # reversed order: the failed run must not become the delta base
    out2 = compare([str(bad), str(good)])
    assert "BENCH_r05.json" in out2["failed"]
    assert not any(r.get("regression") for r in out2["rows"])
    # a budget-exceeded payload that still carries a REAL primary value
    # (the committed BENCH_r04 shape) is a measurement, not a failure
    partial = tmp_path / "partial.json"
    pl = _bench_payload(900, 0.7, 3.1)
    pl["budget_exceeded"] = True
    partial.write_text(json.dumps(pl) + "\n")
    out3 = compare([str(good), str(partial)])
    assert not out3["failed"]
    rows3 = {r["metric"]: r for r in out3["rows"]}
    assert rows3["rows/s"]["values"] == [1000, 900]


# ---------------------------------------------------------------------------
# live resource sampler
# ---------------------------------------------------------------------------

def test_sampler_emits_and_results_bit_identical(tmp_path):
    base = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    df0 = base.create_dataframe(_DATA, num_partitions=2)
    expect = (df0.group_by("k")
              .agg(Alias(F.sum(col("v")), "sv")).to_pydict())
    log = tmp_path / "s.jsonl"
    s = tpu_session({"spark.rapids.sql.test.enabled": "false",
                     "spark.rapids.sample.enabled": "true",
                     "spark.rapids.sample.intervalMs": "10",
                     "spark.rapids.sql.eventLog.path": str(log)})
    try:
        smp = SMP.active_sampler()
        assert smp is not None and smp.running
        df = s.create_dataframe(_DATA, num_partitions=2)
        got = (df.group_by("k")
               .agg(Alias(F.sum(col("v")), "sv")).to_pydict())
        # bit-for-bit: sampling must never perturb results
        assert got == expect
        payload = smp.sample_once()     # deterministic >= 1 sample
        assert payload["pool_limit_bytes"] > 0
        assert "semaphore_holders" in payload
        assert "prefetch_queued_batches" in payload
        assert "active_tasks" in payload
    finally:
        SMP.stop_sampler()
    assert SMP.active_sampler() is None
    events, _ = read_events(str(log))
    samples = [e for e in events if e.kind == "resourceSample"]
    assert samples, "samples must land in the event log"
    assert all(e.query_id == EV.NO_QUERY for e in samples)
    # sampler sink unregistered: later emits go nowhere
    n = len(samples)
    EV.emit("resourceSample", probe=1)
    events2, _ = read_events(str(log))
    assert len([e for e in events2 if e.kind == "resourceSample"]) == n


def test_sampler_confs_validated_at_set_conf():
    s = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    try:
        with pytest.raises(ValueError):
            s.set_conf("spark.rapids.sample.intervalMs", "0")
        with pytest.raises(ValueError):
            s.set_conf("spark.rapids.sample.intervalMs", "nope")
        with pytest.raises(ValueError):
            s.set_conf("spark.rapids.sample.enabled", "maybe")
        with pytest.raises(ValueError):
            C.TpuConf({"spark.rapids.sample.intervalMs": "-1"})
        # toggling through set_conf starts and stops the singleton
        s.set_conf("spark.rapids.sample.enabled", "true")
        assert SMP.active_sampler() is not None
        s.set_conf("spark.rapids.sample.enabled", "false")
        assert SMP.active_sampler() is None
    finally:
        SMP.stop_sampler()


def test_sample_payload_reflects_pool_state(tmp_path):
    """collect_sample reads the real catalog: registering a device batch
    moves the gauges."""
    from spark_rapids_tpu.columnar.batch import batch_from_pydict
    from spark_rapids_tpu.memory.device_manager import get_runtime
    tpu_session({"spark.rapids.sql.test.enabled": "false"})
    rt = get_runtime()
    assert rt is not None
    before = SMP.collect_sample()
    hb = batch_from_pydict({"a": np.arange(4096, dtype=np.int64)})
    h = rt.catalog.add_device_batch(hb.to_device())
    try:
        after = SMP.collect_sample()
        assert after["pool_used_bytes"] > before["pool_used_bytes"]
        assert after["spillable_bytes"] > 0
        assert after["pool_peak_bytes"] >= after["pool_used_bytes"]
    finally:
        rt.catalog.remove(h)


# ---------------------------------------------------------------------------
# event-kind catalog (migrated into the lint rule `event-catalog`; these
# thin tier-1 wrappers keep the invariant in this suite)
# ---------------------------------------------------------------------------

def _run_event_catalog_rule():
    from spark_rapids_tpu.tools.lint import run_lint
    from spark_rapids_tpu.tools.lint.rules import EventCatalogRule
    return run_lint(rules=[EventCatalogRule()], baseline_path="")


def test_every_emit_call_site_uses_cataloged_kind():
    """Every emit()/record_event kind literal is cataloged — now a lint
    rule (tools/lint rules.py `event-catalog`); this wrapper runs the
    rule and asserts zero findings."""
    report = _run_event_catalog_rule()
    offenders = [f.location + ": " + f.message
                 for f in report.active
                 if "not in EVENT_KINDS" in f.message]
    assert not offenders, f"emit sites using uncataloged kinds: {offenders}"


def test_catalog_covers_no_dead_kinds():
    """Every cataloged kind is referenced outside the catalog — the dead
    direction of the same lint rule."""
    report = _run_event_catalog_rule()
    dead = [f.location + ": " + f.message
            for f in report.active if "never referenced" in f.message]
    assert not dead, f"cataloged kinds never referenced: {dead}"


# ---------------------------------------------------------------------------
# Prometheus exposition + ring drop counter
# ---------------------------------------------------------------------------

def _parse_prometheus(text):
    types, samples = {}, {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(" ")
            types[name] = mtype
        elif line and not line.startswith("#"):
            name, value = line.rsplit(" ", 1)
            samples[name] = float(value)
    return types, samples


def test_ring_drops_surface_in_prometheus():
    before = EV.ring_dropped_total()
    ring = EV.RingBufferSink(capacity=2)
    for i in range(7):
        ring.emit(EV.Event("spill", 1, 1, float(i), {}))
    assert ring.dropped == 5
    assert EV.ring_dropped_total() - before == 5
    tpu_session({"spark.rapids.sql.test.enabled": "false"})
    types, samples = _parse_prometheus(EV.render_prometheus())
    name = "spark_rapids_tpu_events_ring_dropped_total"
    assert types[name] == "counter"
    assert samples[name] >= 5


def test_prometheus_format_types_escaping_monotonicity():
    s = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    s.create_dataframe({"a": np.arange(200, dtype=np.int64)}).count()
    text1 = EV.render_prometheus()
    types1, samples1 = _parse_prometheus(text1)
    # every sample line's metric family has a TYPE line (histogram
    # series sample as <family>_bucket/_sum/_count under one TYPE line)
    for name in samples1:
        family = name.split("{")[0]
        for suffix in ("_bucket", "_sum", "_count"):
            base = family[:-len(suffix)] if family.endswith(suffix) else ""
            if types1.get(base) == "histogram":
                family = base
                break
        assert family in types1, f"sample {name} missing # TYPE"
    # new gauges are present
    assert "spark_rapids_tpu_device_pool_peak_bytes" in samples1
    assert "spark_rapids_tpu_device_spillable_bytes" in samples1
    # label escaping: quotes/backslashes in op names must not corrupt
    PROF.reset_range_stats()
    PROF.set_ranges_enabled(True)
    try:
        with PROF.op_range('we"ird\\op'):
            pass
    finally:
        PROF.set_ranges_enabled(False)
    text = EV.render_prometheus()
    assert 'op="we\\"ird\\\\op"' in text
    PROF.reset_range_stats()
    assert EV.escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    # counter monotonicity across more work
    s.create_dataframe({"a": np.arange(200, dtype=np.int64)}).count()
    _, samples2 = _parse_prometheus(EV.render_prometheus())
    for name, mtype in types1.items():
        if mtype != "counter" or name not in samples2:
            continue
        assert samples2.get(name, 0.0) >= samples1.get(name, 0.0), \
            f"counter {name} went backwards"


# ---------------------------------------------------------------------------
# bench smoke contract
# ---------------------------------------------------------------------------

def test_bench_event_log_payload_smoke(tmp_path):
    """bench.py's _event_log_payload must parse a real log and report
    profile_ok (the BENCH smoke assertion)."""
    log = tmp_path / "bench_ev.jsonl"
    _run_logged_query(log)
    import bench
    payload = bench._event_log_payload(str(log))
    assert payload["profile_ok"] is True, payload
    assert payload["queries"] == 1
    assert payload["events"] > 0
    # the per-query transition ledger rides the payload (schema v4)
    (led,) = payload["transitions"].values()
    assert led["d2h_count"] >= 1 and led["d2h_bytes"] > 0
    bad = bench._event_log_payload(str(tmp_path / "missing.jsonl"))
    assert bad["profile_ok"] is False and "error" in bad


# ---------------------------------------------------------------------------
# SPMD distribution: ici bucket, Distribution line, AutoTuner rule 10
# ---------------------------------------------------------------------------

def _ici_log(tmp_path, mesh_align_conf=None, aligned=True):
    log = tmp_path / "ici.jsonl"
    conf = {}
    if mesh_align_conf is not None:
        conf["spark.rapids.sql.adaptive.meshAlign"] = mesh_align_conf
    lines = [
        _jline("queryStart", 21, 1, 1.0, description="mesh q",
               conf=conf),
        _jline("exchangeElided", 21, 1, 1.1, count=2,
               exchanges=["HashPartitioning(k, 8) <= hash[1k,8]",
                          "HashPartitioning(k, 8) <= hash[1k,8]"]),
        _jline("iciExchange", 21, 1, 1.3, devices=8, rows=4000,
               shard_rows=[500] * 8, shard_bytes=1 << 16,
               duration_s=0.4),
        _jline("aqeCoalesce", 21, 1, 1.5, before=16,
               after=8 if aligned else 5, align=8 if aligned else 1,
               mesh=8, ici_active=True, aligned=aligned),
        _jline("spanMetrics", 21, 4, 1.9, parent_id=1, depth=1,
               node="TpuShuffleExchangeExec", desc="x", opTime=0.6,
               start_s=1.0, end_s=2.0),
        _jline("queryEnd", 21, 1, 2.0, duration_s=1.0),
    ]
    log.write_text("\n".join(lines) + "\n")
    return log


def test_profile_ici_bucket_and_distribution_line(tmp_path):
    log = _ici_log(tmp_path)
    profiles, diag = load_profiles(str(log))
    att = attribute(profiles[0])
    assert att.raw["ici"] == pytest.approx(0.4)
    report = render_report(profiles, diag)
    assert "ici" in report
    assert "Distribution: exchangeElided=2 iciExchanges=1" in report
    assert "4000 rows moved in-mesh" in report


def test_autotune_rule10_mesh_misaligned_coalesce(tmp_path):
    """Rule 10: misaligned AQE counts while the ICI path is active and
    meshAlign is OFF -> recommend enabling it, with the aqeCoalesce
    events as evidence."""
    log = _ici_log(tmp_path, mesh_align_conf=False, aligned=False)
    recs = autotune_query(load_profiles(str(log))[0][0])
    by_key = {r.key: r for r in recs}
    rec = by_key["spark.rapids.sql.adaptive.meshAlign"]
    assert rec.current is False and rec.recommended is True
    assert any("aqeCoalesce" in e for e in rec.evidence)
    assert "8-device mesh" in rec.reason
    conf = to_conf_dict([rec])
    C.TpuConf(dict(conf))    # genuinely ready-to-apply


def test_autotune_rule10_quiet_when_aligned_or_enabled(tmp_path):
    # aligned decisions: healthy, no recommendation
    log = _ici_log(tmp_path, mesh_align_conf=False, aligned=True)
    keys = {r.key for r in autotune_query(load_profiles(str(log))[0][0])}
    assert "spark.rapids.sql.adaptive.meshAlign" not in keys
    # misaligned but meshAlign already ON (alignment unachievable):
    # there is no conf to apply — stay silent
    log2 = tmp_path / "on.jsonl"
    log2.write_text(_ici_log(tmp_path, mesh_align_conf=True,
                             aligned=False).read_text())
    keys2 = {r.key
             for r in autotune_query(load_profiles(str(log2))[0][0])}
    assert "spark.rapids.sql.adaptive.meshAlign" not in keys2
