"""TPC-DS q1-q10 differential tests (BASELINE.md milestone #2 at unit
scale): every query runs on the CPU and TPU engines over identical
synthetic data and the row sets must agree."""

import pytest

from spark_rapids_tpu.testing.tpcds import register_tables
from spark_rapids_tpu.testing.tpcds_queries import QUERIES

from tests.asserts import assert_tpu_and_cpu_are_equal_collect


@pytest.mark.parametrize("qname", sorted(QUERIES))
def test_tpcds_query_differential(qname):
    def fn(session):
        register_tables(session, sf=0.02)
        return session.sql(QUERIES[qname])
    assert_tpu_and_cpu_are_equal_collect(
        fn, ignore_order=True,
        conf={"spark.rapids.sql.test.enabled": "false"})


def test_tpcds_queries_return_rows():
    """Sanity: the synthetic data actually produces output for
    representative queries (guards against a datagen regression making the
    differential tests vacuously pass on empty sets).  q2 (weekly sales
    ratios) and q7 (demographic filter) always hit rows."""
    from tests.asserts import cpu_session
    s = cpu_session()
    register_tables(s, sf=0.05)
    assert s.sql(QUERIES["q2"]).collect(), "q2 empty"
    assert s.sql(QUERIES["q7"]).collect(), "q7 empty"
