"""Third-oracle TPC-DS answer validation (VERDICT r4 weak #5).

The differential tier compares the TPU engine against the repo's own CPU
engine — a shared semantics bug would be invisible.  The datagen is
synthetic (documented deviation: docs/compatibility.md), so the published
qualification answer sets do not apply; instead, representative queries
are re-implemented HERE in pandas — an independent third implementation
of the SQL semantics — over the same generated tables, and all three
must agree row for row.
"""

import math

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.testing.tpcds import generate_tables, register_tables
from spark_rapids_tpu.testing.tpcds_queries import QUERIES

from tests.asserts import cpu_session, tpu_session

SF = 0.05


@pytest.fixture(scope="module")
def frames():
    return {name: pd.DataFrame(cols)
            for name, cols in generate_tables(sf=SF).items()}


def _engine_rows(qname):
    out = []
    for s in (cpu_session(),
              tpu_session({"spark.rapids.sql.test.enabled": "false"})):
        register_tables(s, sf=SF)
        out.append(s.sql(QUERIES[qname]).collect())
    return out


def _assert_all_match(expected, qname):
    cpu_rows, tpu_rows = _engine_rows(qname)
    for label, rows in (("cpu", cpu_rows), ("tpu", tpu_rows)):
        assert len(rows) == len(expected), \
            f"{qname} {label}: {len(rows)} rows vs pandas {len(expected)}"
        for i, (got, want) in enumerate(zip(rows, expected)):
            for k, wv in want.items():
                gv = got[k]
                if isinstance(wv, float) and not (wv is None or
                                                  math.isnan(wv)):
                    assert gv == pytest.approx(wv, rel=1e-9), \
                        f"{qname} {label} row {i} col {k}: {gv} vs {wv}"
                else:
                    assert gv == wv, \
                        f"{qname} {label} row {i} col {k}: {gv} vs {wv}"


def test_q3_answers(frames):
    """q3: store_sales x date_dim x item, manufact 128, November,
    group by (d_year, brand_id, brand), order by d_year, sum desc."""
    ss = frames["store_sales"]
    dd = frames["date_dim"]
    it = frames["item"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j = j[(j.i_manufact_id == 128) & (j.d_moy == 11)]
    g = (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
         .agg(sum_agg=("ss_ext_sales_price", "sum")))
    g = g.sort_values(["d_year", "sum_agg", "i_brand_id"],
                      ascending=[True, False, True]).head(100)
    expected = [{"d_year": int(r.d_year), "brand_id": int(r.i_brand_id),
                 "brand": r.i_brand, "sum_agg": float(r.sum_agg)}
                for r in g.itertuples()]
    _assert_all_match(expected, "q3")


def test_q42_answers(frames):
    """q42: (d_year, i_category_id, i_category) sums for manager 1,
    November 2000, ordered by sum desc."""
    ss = frames["store_sales"]
    dd = frames["date_dim"]
    it = frames["item"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j = j[(j.i_manager_id == 1) & (j.d_moy == 11) & (j.d_year == 2000)]
    g = (j.groupby(["d_year", "i_category_id", "i_category"],
                   as_index=False)
         .agg(s=("ss_ext_sales_price", "sum")))
    g = g.sort_values(["s", "d_year", "i_category_id", "i_category"],
                      ascending=[False, True, True, True]).head(100)
    expected = [{"d_year": int(r.d_year),
                 "i_category_id": int(r.i_category_id),
                 "i_category": r.i_category, "s": float(r.s)}
                for r in g.itertuples()]
    _assert_all_match(expected, "q42")


def test_q43_answers(frames):
    """q43: per-store day-name pivot sums for year 2000, gmt offset -5."""
    ss = frames["store_sales"]
    dd = frames["date_dim"]
    st = frames["store"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j = j[(j.d_year == 2000) & (j.s_gmt_offset == -5)]
    days = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
            "Friday", "Saturday"]
    cols = ["sun_sales", "mon_sales", "tue_sales", "wed_sales",
            "thu_sales", "fri_sales", "sat_sales"]
    rows = []
    for (name, sid), grp in j.groupby(["s_store_name", "s_store_id"]):
        rec = {"s_store_name": name, "s_store_id": sid}
        for d, c in zip(days, cols):
            v = grp.loc[grp.d_day_name == d, "ss_sales_price"].sum()
            rec[c] = float(v) if (grp.d_day_name == d).any() else None
        rows.append(rec)
    rows.sort(key=lambda r: (r["s_store_name"], r["s_store_id"]))
    expected = rows[:100]
    _assert_all_match(expected, "q43")


def test_q38_answers(frames):
    """q38: count of (last, first, date) triples present in ALL three
    sales channels within the month window (INTERSECT semantics)."""
    dd = frames["date_dim"]
    cu = frames["customer"]
    win = dd[(dd.d_month_seq >= 1200) & (dd.d_month_seq <= 1211)]

    def triples(fact, datecol, custcol):
        j = frames[fact].merge(win, left_on=datecol, right_on="d_date_sk")
        j = j.merge(cu, left_on=custcol, right_on="c_customer_sk")
        return set(zip(j.c_last_name, j.c_first_name, j.d_date))

    common = (triples("store_sales", "ss_sold_date_sk", "ss_customer_sk")
              & triples("catalog_sales", "cs_sold_date_sk",
                        "cs_bill_customer_sk")
              & triples("web_sales", "ws_sold_date_sk",
                        "ws_bill_customer_sk"))
    expected = [{"col0": len(common)}]
    _assert_all_match(expected, "q38")
