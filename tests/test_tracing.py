"""Query observability tests: span tree, event log round-trip, EXPLAIN
ANALYZE, event-hook fire-once contracts, Prometheus exposition, metric
reset (reference: Spark's SQL event log + GpuTaskMetrics accumulators +
the SQL UI execution graph)."""

import json
import os

import numpy as np
import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu import functions as F
from spark_rapids_tpu.aux import events as EV
from spark_rapids_tpu.aux import profiler as PROF
from spark_rapids_tpu.aux import tracing as TR
from spark_rapids_tpu.aux.metrics import MetricLevel, collect_metrics
from spark_rapids_tpu.columnar import batch_from_pydict
from spark_rapids_tpu.expressions.base import Alias, col, lit

from tests.asserts import tpu_session

RNG = np.random.default_rng(11)


def _sales_dim_session(tmp_path):
    """join + aggregate + sort over parquet — the TPC-DS-class shape the
    acceptance criteria name."""
    s = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    n = 4000
    sales = s.create_dataframe({
        "sk": RNG.integers(0, 50, n).astype(np.int64),
        "qty": RNG.integers(1, 9, n).astype(np.int64),
    }, num_partitions=2)
    pq = str(tmp_path / "sales.parquet")
    sales.write_parquet(pq)
    dim = s.create_dataframe({
        "sk": np.arange(50, dtype=np.int64),
        "name": np.array([f"item{i}" for i in range(50)], dtype=object),
    })
    df = (s.read.parquet(pq)
          .join(dim, on="sk")
          .group_by("name").agg(Alias(F.sum(col("qty")), "q"))
          .order_by("q", ascending=False))
    return s, df


def test_explain_analyze_join_agg_sort(tmp_path):
    s, df = _sales_dim_session(tmp_path)
    text = df.explain(analyze=True)
    assert "== Analyzed Plan" in text
    assert "== Query Summary ==" in text
    # per-node annotations on a real multi-exec tree
    assert "rows=" in text and "batches=" in text and "opTime=" in text
    assert "Agg" in text and "Join" in text and "Sort" in text
    # the run published a summary with task attribution
    qm = TR.last_query_summary()
    assert qm is not None and qm["tasks"] > 0
    assert qm["nodes"], "summary must carry per-node metrics"
    total_rows = sum(n.get("numOutputRows", 0) for n in qm["nodes"])
    assert total_rows > 0


def test_span_tree_mirrors_plan(tmp_path):
    s, df = _sales_dim_session(tmp_path)
    with TR.QueryExecution(description="unit") as qe:
        plan = df._executed_plan()
        for _ in plan.execute_all():
            pass
    execs = [sp for sp in qe._exec_spans()]
    plan_nodes = plan.collect_nodes()
    # reused exchange subtrees may collapse copies onto one metrics dict;
    # every span still corresponds to a plan node and vice versa
    assert len(execs) == len(plan_nodes)
    by_name = {sp.name for sp in execs}
    assert {n.name for n in plan_nodes} == by_name
    # partition child spans exist under executed nodes
    parts = [c for sp in execs for c in sp.children
             if c.kind == "partition"]
    assert parts, "execution must open partition spans"
    assert all(p.end is not None for p in parts)


def test_event_log_roundtrip(tmp_path):
    """Tier-1 schema pin: every emitted event parses and carries
    query_id/span_id plus monotonic timestamps."""
    log = tmp_path / "events.jsonl"
    s = tpu_session({"spark.rapids.sql.test.enabled": "false",
                     "spark.rapids.sql.eventLog.path": str(log)})
    df = s.create_dataframe(
        {"k": RNG.integers(0, 7, 2000), "v": RNG.standard_normal(2000)},
        num_partitions=2)
    df.group_by("k").agg(Alias(F.sum(col("v")), "sv")).collect()
    df.count()
    lines = log.read_text().splitlines()
    assert lines, "event log must not be empty"
    # a fresh file opens with the schema-version header line
    head = json.loads(lines[0])
    assert head["event"] == "eventLogHeader"
    assert head["v"] == EV.EVENT_SCHEMA_VERSION
    kinds = set()
    last_ts = {}
    for line in lines:
        ev = EV.parse_event_line(line)   # raises on schema drift
        raw = json.loads(line)
        for key in ("event", "query_id", "span_id", "ts", "v"):
            assert key in raw, f"event missing {key}: {line}"
        if ev.kind == "eventLogHeader":
            assert raw["query_id"] == EV.NO_QUERY
            continue
        assert raw["query_id"] > 0
        assert raw["span_id"] > 0
        assert isinstance(raw["ts"], float)
        # timestamps are monotonic within each query
        assert raw["ts"] >= last_ts.get(raw["query_id"], 0.0)
        last_ts[raw["query_id"]] = raw["ts"]
        kinds.add(ev.kind)
    assert {"queryStart", "queryEnd", "spanMetrics", "taskEnd"} <= kinds
    assert len(last_ts) >= 2, "both actions must be logged"


def test_spill_and_retry_events_fire_once_each(tmp_path):
    from spark_rapids_tpu.memory import retry as R
    from spark_rapids_tpu.memory.catalog import BufferCatalog

    def make_batch(seed):
        rng = np.random.default_rng(seed)
        return batch_from_pydict({
            "a": rng.integers(0, 1000, 2048).astype(np.int64),
            "b": rng.standard_normal(2048),
        }).to_device()

    cat = BufferCatalog(device_limit_bytes=1 << 20,
                        host_limit_bytes=1 << 30,
                        disk_dir=str(tmp_path))
    with TR.QueryExecution(description="unit-hooks") as qe:
        handles = [cat.add_device_batch(make_batch(i)) for i in range(4)]
        before = cat.spill_count
        cat.synchronous_spill(None)       # push everything spillable off
        spills = [e for e in qe.events() if e.kind == "spill"]
        assert len(spills) == cat.spill_count - before, \
            "exactly one spill event per spilled buffer"
        assert all(e.payload["bytes"] > 0 for e in spills)
        assert all(e.payload["tier"] == "device->host" for e in spills)
        # retry hook: one event per injected-and-retried OOM
        R.force_retry_oom(2)
        R.with_retry_no_split(None, lambda: R.maybe_inject_oom() or 1)
        retries = [e for e in qe.events() if e.kind == "retryOOM"]
        assert len(retries) == 2
        for h in handles:
            cat.remove(h)
    # events got the query's id stamped
    assert all(e.query_id == qe.query_id for e in qe.events())
    summary = qe.summary_dict
    assert summary is not None and summary["status"] == "ok"


def test_split_retry_event_fires_once(tmp_path):
    from spark_rapids_tpu.memory import retry as R
    from spark_rapids_tpu.memory.catalog import BufferCatalog
    from spark_rapids_tpu.memory.spillable import SpillableColumnarBatch

    cat = BufferCatalog(device_limit_bytes=8 << 20,
                        host_limit_bytes=1 << 30, disk_dir=str(tmp_path))
    hb = batch_from_pydict({"a": np.arange(1000, dtype=np.int64)})
    with TR.QueryExecution(description="unit-split") as qe:
        sb = SpillableColumnarBatch.from_host(hb, catalog=cat)
        R.force_split_and_retry_oom(1)
        out = list(R.with_retry(sb, lambda s: R.maybe_inject_oom()
                                or s.row_count))
        assert sum(out) == 1000
        splits = [e for e in qe.events() if e.kind == "splitRetry"]
        assert len(splits) == 1
        assert splits[0].payload["pieces"] == 2


def test_injected_retry_attributed_to_query(tmp_path):
    """Acceptance shape: a forced RetryOOM during a query shows up both
    as events in the JSONL log and as a nonzero retry_count in the query
    summary (the one bench.py embeds)."""
    from spark_rapids_tpu.exec import aggregate as AG
    log = tmp_path / "ev.jsonl"
    s = tpu_session({
        "spark.rapids.sql.test.enabled": "false",
        "spark.rapids.sql.test.injectRetryOOM": "true",
        "spark.rapids.sql.test.agg.forceMergeRepartitionDepth": "1",
        "spark.rapids.sql.eventLog.path": str(log),
    })
    try:
        df = s.create_dataframe(
            {"k": RNG.integers(0, 50, 5000), "v": RNG.integers(0, 9, 5000)},
            num_partitions=2)
        rows = df.group_by("k").agg(Alias(F.sum(col("v")), "s")).collect()
        assert len(rows) == 50
        qm = TR.last_query_summary()
        assert qm is not None and qm["retry_count"] > 0, \
            "query summary must attribute the injected retries"
        events = [json.loads(ln) for ln in log.read_text().splitlines()]
        assert any(e["event"] == "retryOOM" for e in events)
        assert any(e["event"] == "taskEnd" and e.get("retry_count", 0) > 0
                   for e in events)
    finally:
        AG.FORCE_REPARTITION_BELOW_DEPTH = 0
        from spark_rapids_tpu.plan.base import set_task_oom_injection
        set_task_oom_injection("false")


def test_metrics_reset_between_actions():
    """Re-run staleness fix: repeated actions on the same DataFrame report
    per-query metrics, not accumulated ones."""
    s = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    df = (s.create_dataframe({"a": np.arange(1000, dtype=np.int64)})
          .select(Alias(col("a") + lit(1), "b")))
    plan1 = df._executed_plan()
    plan1.collect_host()
    m1 = collect_metrics(plan1)
    plan2 = df._executed_plan()
    plan2.collect_host()
    m2 = collect_metrics(plan2)
    by_node1 = {m["node"]: m.get("numOutputBatches") for m in m1}
    by_node2 = {m["node"]: m.get("numOutputBatches") for m in m2}
    assert by_node1 == by_node2, \
        "second action must not accumulate on top of the first"


def test_metrics_level_validated_at_set_conf():
    s = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    with pytest.raises(ValueError):
        s.set_conf("spark.rapids.sql.metrics.level", "bogus")
    with pytest.raises(ValueError):
        C.TpuConf({"spark.rapids.sql.metrics.level": "bogus"})
    with pytest.raises(ValueError):
        MetricLevel.parse("bogus")
    assert MetricLevel.parse(" debug ") is MetricLevel.DEBUG
    # valid values still round-trip through set_conf
    s.set_conf("spark.rapids.sql.metrics.level", "ESSENTIAL")


def test_op_ranges_cover_exec_names():
    """Satellite: profiler op ranges wire through the exec
    execute_partition wrappers, so traces carry operator names."""
    PROF.reset_range_stats()
    s = tpu_session({"spark.rapids.sql.test.enabled": "false",
                     "spark.rapids.sql.nvtx.enabled": "true"})
    try:
        (s.create_dataframe({"a": np.arange(500, dtype=np.int64)})
         .select(Alias(col("a") * lit(2), "b")).collect())
        stats = PROF.range_stats()
        assert any(name.endswith("Exec") for name in stats), \
            f"expected exec-named ranges, got {sorted(stats)}"
    finally:
        PROF.set_ranges_enabled(False)
        PROF.reset_range_stats()


def test_ranges_disabled_is_default_and_unrecorded():
    PROF.reset_range_stats()
    s = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    (s.create_dataframe({"a": np.arange(100, dtype=np.int64)})
     .select(col("a")).collect())
    assert PROF.range_stats() == {}


def test_render_prometheus_parses():
    s = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    s.create_dataframe({"a": np.arange(100, dtype=np.int64)}).count()
    text = EV.render_prometheus()
    assert "# TYPE spark_rapids_tpu_retry_total counter" in text
    assert "spark_rapids_tpu_device_pool_limit_bytes" in text
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, value = line.rsplit(" ", 1)
        assert name.startswith("spark_rapids_tpu_")
        float(value)   # every sample parses


def test_ring_buffer_bounds_and_counts_drops():
    ring = EV.RingBufferSink(capacity=4)
    for i in range(10):
        ring.emit(EV.Event("x", 1, 1, float(i), {"i": i}))
    evs = ring.events()
    assert len(evs) == 4
    assert ring.dropped == 6
    assert [e.payload["i"] for e in evs] == [6, 7, 8, 9]


def test_emit_without_query_routes_to_global_sink():
    ring = EV.RingBufferSink()
    EV.add_global_sink(ring)
    try:
        EV.emit("heartbeatish", executor_id="exec-1")
        assert len(ring) == 1
        ev = ring.events()[0]
        assert ev.query_id == EV.NO_QUERY
        assert ev.payload["executor_id"] == "exec-1"
    finally:
        EV.remove_global_sink(ring)
    # and with neither query nor sink, emit is a no-op
    EV.emit("dropped-on-floor")


def test_tracing_disabled_by_conf():
    s = tpu_session({"spark.rapids.sql.test.enabled": "false",
                     "spark.rapids.tpu.tracing.enabled": "false"})
    marker = TR.last_query_summary()
    df = s.create_dataframe({"a": np.arange(10, dtype=np.int64)})
    df.collect()
    assert TR.last_query_summary() is marker, \
        "disabled tracing must not publish summaries"


def test_heartbeat_events_attributed():
    from spark_rapids_tpu.shuffle.heartbeat import ShuffleHeartbeatManager
    clock = [0.0]
    mgr = ShuffleHeartbeatManager(timeout_s=5.0, clock=lambda: clock[0])
    with TR.QueryExecution(description="hb") as qe:
        mgr.register_executor("e1")
        mgr.register_executor("e2")
        clock[0] = 10.0
        dead = mgr.expire_dead()
        assert sorted(dead) == ["e1", "e2"]
        kinds = [e.kind for e in qe.events()]
        assert kinds.count("executorRegistered") == 2
        assert kinds.count("executorLost") == 2
