"""Host-transition & device-sync ledger tests: the aux/transitions
gateway (counters, snapshot/delta, conf gating), schema-v4 events and
reader back-compat (v1-v3 still load), the per-query ledger riding
queryEnd into summaries / explain(analyze) / tools profile, the
Chrome-trace ``tools trace`` export (format validation + CLI +
unattributed check), serving latency histograms in the Prometheus
exposition, and the trimodal bit-identity guarantee (instrumentation
on/off never changes results)."""

import json
import math

import numpy as np
import pytest

from spark_rapids_tpu.aux import events as EV
from spark_rapids_tpu.aux import transitions as TR
from spark_rapids_tpu.tools import __main__ as CLI
from spark_rapids_tpu.tools.reader import (SUPPORTED_VERSIONS,
                                           load_profiles, read_events)
from spark_rapids_tpu.tools.trace import (build_trace, render_trace,
                                          trace_from_log,
                                          unattributed_transitions)

from tests.asserts import tpu_session

RNG = np.random.default_rng(31)
_N = 20_000
_DATA = {"k": RNG.integers(0, 11, _N), "v": RNG.standard_normal(_N)}


def _run_logged_query(log, extra=None):
    conf = {"spark.rapids.sql.test.enabled": "false",
            "spark.rapids.sql.eventLog.path": str(log)}
    conf.update(extra or {})
    s = tpu_session(conf)
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.expressions.base import Alias, col
    df = s.create_dataframe(_DATA, num_partitions=2)
    out = df.group_by("k").agg(Alias(F.sum(col("v")), "sv")).collect()
    return s, out


def _jline(kind, query_id, span_id, ts, v=EV.EVENT_SCHEMA_VERSION,
           **payload):
    return json.dumps({"event": kind, "query_id": query_id,
                       "span_id": span_id, "ts": ts, "v": v, **payload})


# ---------------------------------------------------------------------------
# the gateway: counters, snapshot/delta, conf gating
# ---------------------------------------------------------------------------

def test_gateway_counters_and_delta():
    tpu_session({"spark.rapids.sql.test.enabled": "false"})
    start = TR.snapshot()
    TR.record_h2d(1000, 0.25, kinds="dict,flat", planes=3)
    TR.record_d2h(400, 0.125, site="download")
    d = TR.snapshot().delta(start)
    assert d["h2d_count"] == 1 and d["h2d_bytes"] == 1000
    assert d["d2h_count"] == 1 and d["d2h_bytes"] == 400
    assert abs(d["h2d_s"] - 0.25) < 1e-9
    assert abs(d["d2h_s"] - 0.125) < 1e-9
    # ledger keys are the fixed 8-key schema, all JSON-scalar
    assert set(d) == {"h2d_count", "h2d_bytes", "h2d_s", "d2h_count",
                      "d2h_bytes", "d2h_s", "sync_count", "sync_s"}


def test_gateway_fetch_and_sync_count_once():
    """fetch()/sync_int() are deviceSyncs (count forces, scalar syncs);
    only record_d2h (the packed batch download) lands in d2h_* — one
    boundary crossing is never counted in BOTH ledger columns."""
    import jax.numpy as jnp
    tpu_session({"spark.rapids.sql.test.enabled": "false"})
    start = TR.snapshot()
    host = TR.fetch(jnp.arange(128), site="test-fetch")
    assert host.shape == (128,)
    n = TR.sync_int(jnp.asarray(7), site="test-count")
    assert n == 7
    d = TR.snapshot().delta(start)
    assert d["sync_count"] == 2 and d["sync_s"] >= 0.0
    assert d["d2h_count"] == 0, \
        "sync-site fetches must land in sync_*, not d2h_*"


def test_gateway_conf_disable_stops_counting():
    s = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    try:
        s.set_conf("spark.rapids.sql.transitions.enabled", "false")
        assert not TR.enabled()
        start = TR.snapshot()
        TR.record_h2d(999, 0.5)
        TR.record_d2h(999, 0.5)
        d = TR.snapshot().delta(start)
        assert d["h2d_count"] == 0 and d["d2h_count"] == 0
    finally:
        s.set_conf("spark.rapids.sql.transitions.enabled", "true")
        assert TR.enabled()


# ---------------------------------------------------------------------------
# schema v4: events in the log, ledger on queryEnd, reader back-compat
# ---------------------------------------------------------------------------

def test_query_emits_v4_transition_events_and_ledger(tmp_path):
    log = tmp_path / "tr.jsonl"
    _run_logged_query(log)
    events, diag = read_events(str(log))
    assert diag.header_versions == [4]
    kinds = {e.kind for e in events}
    assert "hostTransition" in kinds
    ht = [e for e in events if e.kind == "hostTransition"]
    for e in ht:
        assert e.payload["direction"] in ("h2d", "d2h")
        assert e.payload["bytes"] > 0
        assert e.payload["duration_s"] >= 0.0
        assert e.query_id != EV.NO_QUERY, \
            "transitions during a query must be attributed to it"
    assert {e.payload["direction"] for e in ht} == {"h2d", "d2h"}
    # the queryEnd summary carries the per-query ledger
    qend = [e for e in events if e.kind == "queryEnd"][-1]
    ledger = qend.payload["transitions"]
    assert ledger["h2d_count"] >= 1 and ledger["d2h_count"] >= 1
    assert ledger["h2d_bytes"] > 0 and ledger["d2h_bytes"] > 0


def test_reader_supported_versions_v1_through_v4(tmp_path):
    assert SUPPORTED_VERSIONS == (1, 2, 3, 4)
    # one log per historical version must still load
    for v in (1, 2, 3):
        log = tmp_path / f"v{v}.jsonl"
        lines = [
            _jline("queryStart", 3, 1, 1.0, v=v, description="old"),
            _jline("spanMetrics", 3, 2, 2.0, v=v, node="TpuProjectExec",
                   opTime=0.5),
            _jline("queryEnd", 3, 1, 3.0, v=v, duration_s=2.0),
        ]
        log.write_text("\n".join(lines) + "\n")
        profiles, diag = load_profiles(str(log))
        assert len(profiles) == 1, f"v{v} log must still load"
        assert not diag.unknown_kinds


def test_explain_analyze_renders_transition_footer(tmp_path):
    log = tmp_path / "ex.jsonl"
    s = tpu_session({"spark.rapids.sql.test.enabled": "false",
                     "spark.rapids.sql.eventLog.path": str(log)})
    df = s.create_dataframe(_DATA, num_partitions=2)
    text = df.explain(analyze=True)
    assert "== Transitions ==" in text
    assert "d2h" in text


# ---------------------------------------------------------------------------
# tools profile: transitions + sync buckets, ledger in JSON output
# ---------------------------------------------------------------------------

def test_profile_buckets_and_json_ledger(tmp_path):
    from spark_rapids_tpu.tools.profile import (BUCKETS, attribute,
                                                profiles_to_json,
                                                render_report)
    assert "transitions" in BUCKETS and "sync" in BUCKETS
    log = tmp_path / "prof.jsonl"
    _run_logged_query(log)
    profiles, diag = load_profiles(str(log))
    att = attribute(profiles[-1])
    assert att.scaled["transitions"] > 0.0, \
        "a collect() query crosses the boundary at least once"
    report = render_report(profiles, diag)
    assert "Transitions:" in report
    payload = profiles_to_json(profiles, diag)
    led = payload["queries"][-1]["transitions"]
    assert led["d2h_count"] >= 1 and led["d2h_bytes"] > 0


def test_profile_ledger_survives_event_ring_drop(tmp_path):
    """Attribution must fall back to the queryEnd ledger when the
    individual hostTransition events were dropped/filtered."""
    from spark_rapids_tpu.tools.profile import attribute
    log = tmp_path / "drop.jsonl"
    _run_logged_query(log)
    kept = [ln for ln in open(log).read().splitlines()
            if '"hostTransition"' not in ln and '"deviceSync"' not in ln]
    slim = tmp_path / "slim.jsonl"
    slim.write_text("\n".join(kept) + "\n")
    profiles, _ = load_profiles(str(slim))
    att = attribute(profiles[-1])
    assert att.scaled["transitions"] > 0.0, \
        "queryEnd ledger must back-fill the bucket"


# ---------------------------------------------------------------------------
# tools trace: Chrome trace-event format + CLI + unattributed check
# ---------------------------------------------------------------------------

def _validate_chrome_trace(trace):
    """The subset of the Trace Event Format spec Perfetto requires."""
    assert isinstance(trace, dict)
    assert isinstance(trace["traceEvents"], list)
    for ev in trace["traceEvents"]:
        assert ev["ph"] in ("M", "X", "C"), ev
        assert isinstance(ev["pid"], int)
        if ev["ph"] == "M":
            assert ev["name"] in ("process_name", "thread_name")
            assert "name" in ev["args"]
        elif ev["ph"] == "X":
            assert isinstance(ev["name"], str) and ev["name"]
            assert ev["ts"] >= 0 and ev["dur"] >= 0
            assert isinstance(ev["tid"], int)
        elif ev["ph"] == "C":
            assert ev["ts"] >= 0 and isinstance(ev["args"], dict)
    # must survive a strict JSON round trip (what the UI actually loads)
    assert json.loads(render_trace(trace)) == json.loads(
        json.dumps(trace, default=str))


def test_trace_export_is_valid_chrome_trace(tmp_path):
    log = tmp_path / "trace.jsonl"
    _run_logged_query(log)
    trace, unattributed, _ = trace_from_log(str(log))
    assert unattributed == 0
    _validate_chrome_trace(trace)
    evs = trace["traceEvents"]
    slices = [e for e in evs if e["ph"] == "X"]
    assert any(e["cat"] == "plan" for e in slices)
    assert any(e["cat"] == "hostTransition" for e in slices)
    # thread metadata names the transitions track
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               and e["args"]["name"] == "transitions" for e in evs)


def test_trace_cli_roundtrip_and_check(tmp_path, capsys):
    log = tmp_path / "cli.jsonl"
    _run_logged_query(log)
    out = tmp_path / "trace.json"
    rc = CLI.main(["trace", str(log), "-o", str(out), "--check"])
    assert rc == 0
    _validate_chrome_trace(json.loads(out.read_text()))
    capsys.readouterr()
    # an unattributed transition (query_id -1) fails --check
    bad = tmp_path / "bad.jsonl"
    bad.write_text(_jline("hostTransition", EV.NO_QUERY, -1, 1.0,
                          direction="h2d", bytes=10,
                          duration_s=0.01) + "\n")
    assert CLI.main(["trace", str(bad), "-o",
                     str(tmp_path / "bad.json"), "--check"]) == 1
    err = capsys.readouterr().err
    assert "unattributed" in err


def test_unattributed_counter_counts_only_orphans(tmp_path):
    log = tmp_path / "mix.jsonl"
    log.write_text("\n".join([
        _jline("queryStart", 1, 1, 1.0, description="q"),
        _jline("hostTransition", 1, -1, 1.5, direction="d2h",
               bytes=8, duration_s=0.001),
        _jline("deviceSync", EV.NO_QUERY, -1, 1.6, site="stray",
               duration_s=0.002),
        _jline("queryEnd", 1, 1, 2.0, duration_s=1.0),
    ]) + "\n")
    events, _ = read_events(str(log))
    assert unattributed_transitions(events) == 1


def test_trace_empty_profiles_still_valid():
    _validate_chrome_trace(build_trace([]))


# ---------------------------------------------------------------------------
# serving latency histograms in the Prometheus exposition
# ---------------------------------------------------------------------------

def test_latency_histogram_buckets_cumulative():
    from spark_rapids_tpu.serving.server import (LATENCY_BUCKETS,
                                                 LatencyHistogram)
    h = LatencyHistogram()
    for v in (0.0005, 0.003, 0.003, 0.08, 7.0, 1e9):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 6
    assert abs(snap["sum"] - (0.0005 + 0.003 + 0.003 + 0.08 + 7.0 + 1e9)
               ) < 1e-6
    les = [le for le, _ in snap["buckets"]]
    assert les == sorted(les) and les[-1] == math.inf
    counts = [c for _, c in snap["buckets"]]
    assert counts == sorted(counts), "cumulative counts must be monotone"
    assert counts[-1] == snap["count"], "+Inf bucket equals _count"
    assert LATENCY_BUCKETS[-1] == math.inf


def test_prometheus_serving_histogram_exposition():
    from spark_rapids_tpu.serving import server as SRV
    tpu_session({"spark.rapids.sql.test.enabled": "false"})
    SRV.observe_latency("e2e", 0.042)
    SRV.observe_latency("e2e", 3.5)
    SRV.observe_latency("plan", 0.002)
    text = EV.render_prometheus()
    fam = "spark_rapids_tpu_serving_latency_seconds"
    assert f"# TYPE {fam} histogram" in text
    stage_series = {}
    for line in text.splitlines():
        if line.startswith(fam + "_bucket{"):
            labels, value = line.rsplit(" ", 1)
            stage = labels.split('stage="')[1].split('"')[0]
            le = labels.split('le="')[1].split('"')[0]
            stage_series.setdefault(stage, []).append((le, float(value)))
    assert "e2e" in stage_series and "plan" in stage_series
    for stage, series in stage_series.items():
        counts = [c for _, c in series]
        assert counts == sorted(counts), \
            f"{stage}: cumulative bucket counts must be monotone"
        assert series[-1][0] == "+Inf"
        # _count equals the +Inf bucket, _sum present
        cnt = [ln for ln in text.splitlines()
               if ln.startswith(f'{fam}_count{{stage="{stage}"}}')]
        assert cnt and float(cnt[0].rsplit(" ", 1)[1]) == counts[-1]
        assert any(ln.startswith(f'{fam}_sum{{stage="{stage}"}}')
                   for ln in text.splitlines())


def test_prometheus_transition_counters_present():
    tpu_session({"spark.rapids.sql.test.enabled": "false"})
    TR.record_h2d(64, 0.001)
    text = EV.render_prometheus()
    for name in ("h2d_transitions_total", "h2d_bytes_total",
                 "d2h_transitions_total", "d2h_bytes_total",
                 "device_syncs_total"):
        assert f"spark_rapids_tpu_{name}" in text, name


def test_serving_stage_decomposition_rides_admission_event(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_tpu.serving import QueryServer
    from spark_rapids_tpu.serving.server import STAGE_KEYS
    rng = np.random.default_rng(5)
    t = pa.table({"k": rng.integers(0, 5, 2000).astype(np.int64),
                  "v": rng.standard_normal(2000)})
    path = str(tmp_path / "t.parquet")
    pq.write_table(t, path)
    s = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    s.create_or_replace_temp_view("t", s.read.parquet(path))
    # completion events fire OUTSIDE any query scope; a global ring sink
    # is where they land (the live sampler registers one the same way)
    ring = EV.RingBufferSink(capacity=256)
    EV.add_global_sink(ring)
    try:
        srv = QueryServer(session=s)
        try:
            sub = srv.submit("SELECT k, SUM(v) AS s FROM t GROUP BY k "
                             "ORDER BY k")
            sub.result(120)
        finally:
            srv.stop()
    finally:
        EV.remove_global_sink(ring)
    stages = sub.info["stages"]
    assert set(stages) == set(STAGE_KEYS)
    assert all(v >= 0.0 for v in stages.values())
    assert stages["plan_s"] > 0.0 and stages["execute_s"] >= 0.0
    # the complete servingAdmission event carries the decomposition
    done = [e for e in ring.events() if e.kind == "servingAdmission"
            and e.payload.get("op") == "complete"]
    assert done, "completion must emit a servingAdmission event"
    pay = done[-1].payload
    assert pay["resolved"] == "planned"
    for k in STAGE_KEYS:
        assert k in pay and pay[k] >= 0.0


# ---------------------------------------------------------------------------
# bit-identity: instrumentation must never change results
# ---------------------------------------------------------------------------

def test_trimodal_bit_identity():
    """Same query under (events on, counters-only, fully disabled)
    produces bit-identical rows — the gateway observes, never
    perturbs."""
    modes = [
        {"spark.rapids.sql.transitions.enabled": "true",
         "spark.rapids.sql.transitions.events": "true"},
        {"spark.rapids.sql.transitions.enabled": "true",
         "spark.rapids.sql.transitions.events": "false"},
        {"spark.rapids.sql.transitions.enabled": "false"},
    ]
    results = []
    try:
        for extra in modes:
            conf = {"spark.rapids.sql.test.enabled": "false"}
            conf.update(extra)
            s = tpu_session(conf)
            from spark_rapids_tpu import functions as F
            from spark_rapids_tpu.expressions.base import Alias, col
            df = s.create_dataframe(_DATA, num_partitions=2)
            rows = (df.filter(col("v") > 0.0).group_by("k")
                    .agg(Alias(F.sum(col("v")), "sv"),
                         Alias(F.count(col("v")), "c"))
                    .sort("k").collect())
            results.append(rows)
    finally:
        tpu_session({"spark.rapids.sql.test.enabled": "false"})
    for rows in results[1:]:
        assert len(rows) == len(results[0])
        for a, b in zip(results[0], rows):
            assert a["k"] == b["k"] and a["c"] == b["c"]
            # bit identity, not approx: instrumentation is pure
            assert np.float64(a["sv"]).tobytes() == \
                np.float64(b["sv"]).tobytes()
