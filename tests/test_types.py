import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T


def test_singletons_and_names():
    assert T.INT.simple_name == "integer"
    assert T.STRING.simple_name == "string"
    assert T.DecimalType(12, 2).simple_name == "decimal(12,2)"
    assert T.ArrayType(T.INT).simple_name == "array<integer>"


def test_equality_and_hash():
    assert T.IntegerType() == T.INT
    assert T.DecimalType(10, 2) == T.DecimalType(10, 2)
    assert T.DecimalType(10, 2) != T.DecimalType(11, 2)
    assert hash(T.LongType()) == hash(T.LONG)
    assert T.StructType([T.StructField("a", T.INT)]) == \
        T.StructType([T.StructField("a", T.INT)])


def test_classification():
    assert T.INT.is_numeric and T.INT.is_integral
    assert T.DOUBLE.is_floating and not T.DOUBLE.is_integral
    assert T.DecimalType(20, 2).is_decimal128
    assert not T.DecimalType(18, 2).is_decimal128
    assert T.ArrayType(T.INT).is_nested


def test_arrow_roundtrip():
    for dt in [T.BOOLEAN, T.BYTE, T.SHORT, T.INT, T.LONG, T.FLOAT, T.DOUBLE,
               T.STRING, T.BINARY, T.DATE, T.TIMESTAMP, T.DecimalType(20, 4),
               T.ArrayType(T.LONG), T.MapType(T.STRING, T.INT),
               T.StructType([T.StructField("x", T.INT)])]:
        assert T.from_arrow(T.to_arrow(dt)) == dt


def test_from_numpy():
    assert T.from_numpy_dtype(np.int32) == T.INT
    assert T.from_numpy_dtype(np.float64) == T.DOUBLE
    assert T.from_numpy_dtype(np.bool_) == T.BOOLEAN


def test_common_type():
    assert T.common_type(T.INT, T.LONG) == T.LONG
    assert T.common_type(T.INT, T.DOUBLE) == T.DOUBLE
    assert T.common_type(T.NULL, T.STRING) == T.STRING
    assert T.common_type(T.DecimalType(10, 2), T.DecimalType(12, 4)) == \
        T.DecimalType(12, 4)
    assert T.common_type(T.DATE, T.TIMESTAMP) == T.TIMESTAMP
    with pytest.raises(TypeError):
        T.common_type(T.ArrayType(T.INT), T.INT)


def test_decimal_bounds():
    with pytest.raises(ValueError):
        T.DecimalType(39, 0)
    with pytest.raises(ValueError):
        T.DecimalType(5, 7)
