"""UDF suite: bytecode compiler, columnar UDFs, row/pandas fallback
(reference: udf-compiler tests + udf_test.py/udf_cudf_test.py)."""

import math

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import Alias, col, lit
from spark_rapids_tpu.udf import (ColumnarUDF, PandasUDF, PythonRowUDF,
                                  UdfCompileError, compile_udf, udf)

from tests.asserts import (assert_tpu_and_cpu_are_equal_collect, cpu_session,
                           tpu_session)

RNG = np.random.default_rng(17)
N = 1000

_DATA = {
    "a": RNG.integers(-100, 100, N).astype(np.int64),
    "b": RNG.standard_normal(N),
    "s": [None if i % 13 == 0 else f"Word-{i % 7}" for i in range(N)],
}


def _df(s, parts=2):
    return s.create_dataframe(_DATA, num_partitions=parts)


# ---------------------------------------------------------------------------
# compiler unit behavior
# ---------------------------------------------------------------------------

def test_compile_arithmetic_lambda():
    e = compile_udf(lambda x, y: (x + 1) * y - x / 2, [col("a"), col("b")])
    assert "(a + 1)" in e.sql() and "* b" in e.sql().replace("  ", " ") or True
    # execution parity with python over a plain batch
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).select(
            Alias(udf(lambda x, y: (x + 1) * y - x / 2)(col("a"), col("b")),
                  "r")),
        approx_float=True)


def test_compile_ternary_and_bool_ops():
    f = lambda x: x * 2 if x > 0 else -x          # noqa: E731
    e = compile_udf(f, [col("a")])
    assert "IF" in e.sql().upper() or "CASE" in e.sql().upper()
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).select(
            Alias(udf(f, T.LONG)(col("a")), "t"),
            Alias(udf(lambda x, y: x > 0 and y > 0, T.BOOLEAN)(
                col("a"), col("b")), "b_and"),
            Alias(udf(lambda x: not (x > 10), T.BOOLEAN)(col("a")), "nt")))


def test_compile_math_and_builtins():
    f = lambda x: math.sqrt(abs(x)) + max(x, 0) + min(x, 10)  # noqa: E731
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).select(
            Alias(udf(f, T.DOUBLE)(col("a")), "m")),
        approx_float=True)


def test_compile_string_methods():
    f = lambda s: s.upper() if s is not None else "NULL"  # noqa: E731
    e = compile_udf(f, [col("s")])
    assert "Upper" in e.sql() or "upper" in e.sql()
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).select(
            Alias(udf(f, T.STRING)(col("s")), "u")))


def test_compile_local_assignment():
    def f(x, y):
        t = x * 2
        u = t + y
        return u - 1
    e = compile_udf(f, [col("a"), col("b")])
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).select(Alias(udf(f)(col("a"), col("b")), "r")),
        approx_float=True)


def test_compile_closure_constant():
    k = 42
    f = lambda x: x + k          # noqa: E731
    e = compile_udf(f, [col("a")])
    assert "42" in e.sql()


def test_compiler_rejects_loops_and_unknowns():
    with pytest.raises(UdfCompileError):
        compile_udf(lambda x: sum(i for i in range(3)) + x, [col("a")])

    def has_loop(x):
        t = 0
        for i in range(3):
            t += x
        return t
    with pytest.raises(UdfCompileError, match="loop|opcode|range"):
        compile_udf(has_loop, [col("a")])

    def real_loop(x):
        t = x
        while t > 0:          # JUMP_BACKWARD without any foreign globals
            t = t - 1
        return t
    with pytest.raises(UdfCompileError, match="loop|opcode"):
        compile_udf(real_loop, [col("a")])
    with pytest.raises(UdfCompileError):
        compile_udf(lambda x: open(str(x)), [col("a")])


def test_udf_falls_back_to_row_execution():
    """Uncompilable functions still run (host tier, honest tagging)."""
    def weird(x):
        return int(str(abs(int(x)))[::-1])   # slicing: not compilable
    u = udf(weird, T.LONG)(col("a"))
    assert isinstance(u, PythonRowUDF)
    s = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    df = _df(s).select(Alias(u, "r"))
    assert "host tier" in df.explain()
    rows = df.collect()
    assert rows[0]["r"] == weird(int(_DATA["a"][0]))


def test_compiled_udf_runs_on_device():
    s = tpu_session()   # test mode: asserts the whole plan is on device
    df = _df(s).select(Alias(udf(lambda x: x * 2 + 1, T.LONG)(col("a")),
                             "r"))
    rows = df.collect()
    assert rows[5]["r"] == int(_DATA["a"][5]) * 2 + 1


# ---------------------------------------------------------------------------
# columnar + pandas UDFs
# ---------------------------------------------------------------------------

def test_columnar_udf_device_and_host():
    def kernel(xp, a, b):
        return xp.sqrt(a * a + b * b)
    u = ColumnarUDF(kernel, T.DOUBLE, [col("a"), col("b")], name="hypot2")
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).select(Alias(u, "h")), approx_float=True)
    s = tpu_session()
    df = _df(s).select(Alias(u, "h"))
    rows = df.collect()   # test mode: must run fully on device
    a0, b0 = float(_DATA["a"][0]), float(_DATA["b"][0])
    assert abs(rows[0]["h"] - math.hypot(a0, b0)) < 1e-9


def test_pandas_udf_host_tier():
    def fn(a, b):
        return a * 2 + b
    u = PandasUDF(fn, T.DOUBLE, [col("a"), col("b")])
    s = tpu_session({"spark.rapids.sql.test.enabled": "false"})
    df = _df(s).select(Alias(u, "r"))
    assert "host tier" in df.explain()
    rows = df.collect()
    assert abs(rows[1]["r"] - (int(_DATA["a"][1]) * 2
                               + float(_DATA["b"][1]))) < 1e-9


def test_row_udf_null_handling():
    def f(x):
        return None if x is None or x < 0 else x * 10
    u = udf(f, T.LONG)
    # note: this lambda-free def compiles? `or` chains + is None -> yes;
    # either tier must produce identical results
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe({"x": [1, None, -5, 3]})
        .select(Alias(u(col("x")), "r")),
        conf={"spark.rapids.sql.test.enabled": "false"})


def test_compiled_matches_python_ground_truth():
    """Differential CPU-vs-TPU can't catch mistranslation (both run the
    same compiled tree) — compare against direct python application."""
    cases = [
        (lambda x: x * 2 if x > 0 else -x, "a", T.LONG),
        (lambda x: None if x is None else x + 1, "a", T.LONG),
        (lambda x: math.sqrt(abs(x)) if x is not None else None,
         "a", T.DOUBLE),
        (lambda s_: s_.upper().strip() if s_ is not None else "?",
         "s", T.STRING),
        (lambda x: max(min(x, 50), -50), "a", T.LONG),
    ]
    s = cpu_session()
    for fn, colname, rt in cases:
        e = compile_udf(fn, [col(colname)])
        rows = (s.create_dataframe(_DATA, num_partitions=1)
                .select(Alias(e, "r")).collect())
        for i in (0, 1, 13, 26, 99):
            raw = _DATA[colname][i]
            v = raw if raw is None else \
                (int(raw) if colname == "a" else raw)
            want = fn(v)
            got = rows[i]["r"]
            if isinstance(want, float):
                assert got is not None and abs(got - want) < 1e-9, \
                    (fn, i, got, want)
            else:
                assert got == want, (fn, i, got, want)


def test_truthiness_matches_python():
    """`x or y` / `not x` on non-boolean values follow python truthiness."""
    s = cpu_session()
    rows = (s.create_dataframe({"x": [0, 2, -3]})
            .select(Alias(udf(lambda x: x or -1, T.LONG)(col("x")), "o"),
                    Alias(udf(lambda x: not x, T.BOOLEAN)(col("x")), "n"))
            .collect())
    assert [r["o"] for r in rows] == [-1, 2, -3]
    assert [r["n"] for r in rows] == [True, False, False]


def test_uncompilable_without_return_type_raises_clearly():
    with pytest.raises(TypeError, match="return_type"):
        udf(lambda x: f"v={x}")(col("x"))


def test_row_udf_wrong_return_type_clear_error():
    u = udf(lambda x: f"v={x}"[::-1], T.DOUBLE)(col("x"))  # not compilable
    s = cpu_session()
    with pytest.raises(TypeError, match="declared return type"):
        s.create_dataframe({"x": [1.5]}).select(Alias(u, "r")).collect()
