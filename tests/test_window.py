"""Differential window function tests (reference: integration_tests
window_function_test.py over assert_gpu_and_cpu_are_equal_collect)."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.functions import Window

from tests.asserts import assert_tpu_and_cpu_are_equal_collect


def _data():
    return {
        "g": [1, 1, 1, 2, 2, None, 3, 3, 3, 3],
        "o": [3, 1, 2, 5, 5, 1, None, 2, 9, 4],
        "v": [1.0, 2.0, None, 4.0, 5.0, 6.0, 7.0, None, 9.0, 10.0],
    }


W_GO = lambda: Window.partition_by("g").order_by("o")


@pytest.mark.parametrize("fn", [F.row_number, F.rank, F.dense_rank],
                         ids=["row_number", "rank", "dense_rank"])
@pytest.mark.parametrize("nparts", [1, 3])
def test_ranking(fn, nparts):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_data(), num_partitions=nparts)
        .select(F.col("g"), F.col("o"),
                F.Alias(fn().over(W_GO()), "r")),
        ignore_order=True)


def test_rank_with_ties():
    # o has duplicates within g=2: rank skips, dense_rank doesn't
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_data(), num_partitions=2)
        .select(F.col("g"), F.col("o"),
                F.Alias(F.rank().over(W_GO()), "r"),
                F.Alias(F.dense_rank().over(W_GO()), "dr"),
                F.Alias(F.row_number().over(W_GO()), "rn")),
        ignore_order=True)


def test_ntile():
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_data(), num_partitions=2)
        .select(F.col("g"), F.col("o"),
                F.Alias(F.ntile(3).over(W_GO()), "t")),
        ignore_order=True)


@pytest.mark.parametrize("off", [1, 2])
def test_lag_lead(off):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_data(), num_partitions=2)
        .select(F.col("g"), F.col("o"), F.col("v"),
                F.Alias(F.lag("v", off).over(W_GO()), "lg"),
                F.Alias(F.lead("v", off).over(W_GO()), "ld")),
        ignore_order=True)


@pytest.mark.parametrize("agg", [F.sum, F.min, F.max, F.count, F.avg],
                         ids=["sum", "min", "max", "count", "avg"])
def test_running_agg_default_frame(agg):
    # default frame with ORDER BY: RANGE unbounded-preceding..current row
    # (peers included — o=5 is duplicated in g=2)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_data(), num_partitions=2)
        .select(F.col("g"), F.col("o"), F.col("v"),
                F.Alias(agg("v").over(W_GO()), "a")),
        ignore_order=True)


@pytest.mark.parametrize("agg", [F.sum, F.min, F.max, F.count, F.avg],
                         ids=["sum", "min", "max", "count", "avg"])
def test_whole_partition_agg(agg):
    # no ORDER BY -> whole partition frame
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_data(), num_partitions=2)
        .select(F.col("g"), F.col("v"),
                F.Alias(agg("v").over(Window.partition_by("g")), "a")),
        ignore_order=True)


@pytest.mark.parametrize("frame", [(-1, 1), (-2, 0), (0, 2), (-3, -1),
                                   (1, 3)])
@pytest.mark.parametrize("agg", [F.sum, F.min, F.max, F.count, F.avg],
                         ids=["sum", "min", "max", "count", "avg"])
def test_bounded_rows_frames(agg, frame):
    lo, hi = frame
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_data(), num_partitions=2)
        .select(F.col("g"), F.col("o"), F.col("v"),
                F.Alias(agg("v").over(
                    W_GO().rows_between(lo, hi)), "a")),
        ignore_order=True)


@pytest.mark.parametrize("agg", [F.sum, F.min, F.max],
                         ids=["sum", "min", "max"])
def test_rows_unbounded_frames(agg):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_data(), num_partitions=2)
        .select(F.col("g"), F.col("o"), F.col("v"),
                F.Alias(agg("v").over(W_GO().rows_between(
                    Window.unboundedPreceding, Window.currentRow)), "run"),
                F.Alias(agg("v").over(W_GO().rows_between(
                    0, Window.unboundedFollowing)), "rev"),
                F.Alias(agg("v").over(W_GO().rows_between(
                    Window.unboundedPreceding,
                    Window.unboundedFollowing)), "all")),
        ignore_order=True)


def test_multiple_specs_one_select():
    # two different partition/order specs => two chained WindowExecs
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_data(), num_partitions=2)
        .select(F.col("g"), F.col("o"), F.col("v"),
                F.Alias(F.row_number().over(W_GO()), "rn"),
                F.Alias(F.sum("v").over(
                    Window.partition_by("o").order_by("g")), "s2")),
        ignore_order=True)


def test_window_no_partition():
    # global window: single partition ordering over everything
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_data(), num_partitions=3)
        .select(F.col("o"), F.col("v"),
                F.Alias(F.row_number().over(Window.order_by("o", "v")),
                        "rn")),
        ignore_order=True)


def test_window_string_partition_keys():
    data = {"g": ["a", "a", "b", None, "b", "a"],
            "o": [3, 1, 2, 5, 4, 2],
            "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(data, num_partitions=2)
        .select(F.col("g"), F.col("o"),
                F.Alias(F.row_number().over(W_GO()), "rn"),
                F.Alias(F.sum("v").over(W_GO()), "rs")),
        ignore_order=True)


def test_window_desc_order():
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_data(), num_partitions=2)
        .select(F.col("g"), F.col("o"),
                F.Alias(F.row_number().over(
                    Window.partition_by("g").order_by(F.desc("o"))), "rn")),
        ignore_order=True)


def test_window_with_column_and_expr():
    # window result used inside a bigger projection expression
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_data(), num_partitions=2)
        .with_column("pct", F.col("v") / F.sum("v").over(
            Window.partition_by("g"))),
        ignore_order=True)


def test_window_int_sum_types():
    data = {"g": [1, 1, 2, 2], "o": [1, 2, 1, 2],
            "i": np.array([5, 6, 7, 8], dtype=np.int32)}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(data, num_partitions=2)
        .select(F.col("g"),
                F.Alias(F.sum("i").over(W_GO()), "s"),
                F.Alias(F.count("*").over(W_GO()), "c")),
        ignore_order=True)


def test_window_larger_random():
    rng = np.random.default_rng(7)
    n = 4000
    data = {"g": rng.integers(0, 50, n), "o": rng.integers(0, 1000, n),
            "v": rng.normal(size=n)}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(data, num_partitions=3)
        .select(F.col("g"), F.col("o"),
                F.Alias(F.row_number().over(W_GO()), "rn"),
                F.Alias(F.sum("v").over(W_GO().rows_between(-3, 3)), "s")),
        ignore_order=True)


def test_lag_lead_default():
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_data(), num_partitions=2)
        .select(F.col("g"), F.col("o"),
                F.Alias(F.lag("v", 1, -99.0).over(W_GO()), "lg"),
                F.Alias(F.lead("v", 2, -1.0).over(W_GO()), "ld")),
        ignore_order=True)


def test_window_nan_order_key_peers():
    # NaN order keys are peers of each other (Spark: NaN == NaN in ordering)
    data = {"g": [1, 1, 1, 1], "o": [float("nan"), float("nan"), 1.0, 2.0],
            "v": [1.0, 2.0, 3.0, 4.0]}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(data)
        .select(F.col("o"), F.Alias(F.rank().over(W_GO()), "r"),
                F.Alias(F.sum("v").over(W_GO()), "rs")),
        ignore_order=True)


def test_window_rejected_outside_projection():
    import pytest as _pt
    from tests.asserts import cpu_session
    s = cpu_session()
    df = s.create_dataframe(_data())
    w = F.row_number().over(W_GO())
    with _pt.raises(ValueError, match="window expressions"):
        df.filter(w <= 1)
    with _pt.raises(ValueError, match="window expressions"):
        df.order_by(w)


def test_bounded_range_frame_rejected():
    import pytest as _pt
    from tests.asserts import cpu_session
    s = cpu_session()
    df = s.create_dataframe(_data())
    with _pt.raises(NotImplementedError, match="RANGE"):
        df.select(F.Alias(F.sum("v").over(
            W_GO().range_between(-1, 0)), "a")).collect()


# -- batched running windows (GpuRunningWindowExec.scala:220 analog) --------

#: session conf forcing the running path AND the sort stage's external
#: chunking — in production both engage together under the same memory
#: pressure (module globals are overwritten from conf at every plan
#: compile, so tests arm via conf)
RUNNING_CONF = {"spark.rapids.sql.test.window.forceRunning": "true",
                "spark.rapids.sql.test.sort.forceOutOfCore": "true"}


@pytest.fixture
def force_running_window():
    """Small merge chunks so the carry crosses several batches."""
    from spark_rapids_tpu.exec import sort as S
    from spark_rapids_tpu.exec import window as W
    prev_rows = S._MERGE_OUT_ROWS
    S._MERGE_OUT_ROWS = 700
    yield W
    S._MERGE_OUT_ROWS = prev_rows


def _big_data(n=6000, ngroups=7, seed=2):
    rng = np.random.default_rng(seed)
    g = rng.integers(0, ngroups, n)
    o = rng.integers(0, 50, n)          # heavy ties -> peer groups
    v = rng.normal(size=n)
    v = np.where(rng.random(n) < 0.04, np.nan, v)
    import pyarrow as pa
    vmask = rng.random(n) < 0.08
    return {"g": pa.array(g), "o": pa.array(o),
            "v": pa.array(v, mask=vmask)}


def _running_frame():
    return W_GO().rows_between(Window.unbounded_preceding,
                               Window.current_row)


def test_running_window_ranks_multi_batch(force_running_window):
    Wm = force_running_window
    before = Wm.RUNNING_WINDOW_EVENTS
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_big_data(), num_partitions=4)
        .select(F.col("g"), F.col("o"),
                F.Alias(F.row_number().over(W_GO()), "rn"),
                F.Alias(F.rank().over(W_GO()), "r"),
                F.Alias(F.dense_rank().over(W_GO()), "dr")),
        ignore_order=True, conf=RUNNING_CONF)
    assert Wm.RUNNING_WINDOW_EVENTS > before, "running path did not engage"


def test_running_window_aggs_multi_batch(force_running_window):
    # unique order keys: running sums over TIED keys are tie-order
    # dependent and so not comparable across engines with NaN present
    d = _big_data()
    d["o"] = np.arange(len(d["o"]))
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(d, num_partitions=4)
        .select(F.col("g"), F.col("o"), F.col("v"),
                F.Alias(F.sum("v").over(_running_frame()), "rs"),
                F.Alias(F.count("v").over(_running_frame()), "rc"),
                F.Alias(F.min("v").over(_running_frame()), "rmin"),
                F.Alias(F.max("v").over(_running_frame()), "rmax")),
        ignore_order=True, approx_float=True, conf=RUNNING_CONF)


def test_running_window_single_group_spans_batches(force_running_window):
    """One partition key across every batch: the carry chains through
    the whole stream."""
    n = 3000
    rng = np.random.default_rng(9)
    d = {"g": np.ones(n, dtype=np.int64),
         "o": np.arange(n) % 97,
         "v": rng.integers(0, 10, n).astype(np.int64)}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(d, num_partitions=3)
        .select(F.col("o"),
                F.Alias(F.row_number().over(W_GO()), "rn"),
                F.Alias(F.rank().over(W_GO()), "r"),
                F.Alias(F.sum("v").over(_running_frame()), "rs")),
        ignore_order=True, conf=RUNNING_CONF)


def test_running_window_not_eligible_falls_back(force_running_window):
    """lag is not a running shape -> the concat path must be used and
    still match."""
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_big_data(1500), num_partitions=3)
        .select(F.col("g"), F.col("o"), F.col("v"),
                F.Alias(F.lag("v", 1).over(W_GO()), "lg")),
        ignore_order=True, approx_float=True, conf=RUNNING_CONF)


def test_window_sum_nan_inf_no_poison():
    """One NaN/inf must affect only frames CONTAINING it — the prefix-sum
    difference trick would otherwise poison every later row (found by the
    running-window differential tests, fixed in ops/window_ops.py)."""
    import pyarrow as pa
    d = {"g": pa.array([0, 0, 0, 1, 1, 2, 2, 3, 3]),
         "o": pa.array(list(range(9))),
         "v": pa.array([1.0, float("nan"), 2.0, float("inf"), 3.0,
                        float("-inf"), float("inf"), 4.0, 5.0])}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(d, num_partitions=1)
        .select(F.col("g"), F.col("o"),
                F.Alias(F.sum("v").over(_running_frame()), "rs"),
                F.Alias(F.avg("v").over(_running_frame()), "ra"),
                F.Alias(F.min("v").over(_running_frame()), "rmin"),
                F.Alias(F.max("v").over(_running_frame()), "rmax")),
        ignore_order=True, approx_float=True)


# ---------------------------------------------------------------------------
# chunked bounded-frame windows (reference: GpuBatchedBoundedWindowExec —
# carry a max(preceding)+max(following) tail between batches)
# ---------------------------------------------------------------------------

BOUNDED_CONF = {"spark.rapids.sql.test.window.forceBoundedBatched": "true",
                "spark.rapids.sql.test.sort.forceOutOfCore": "true"}


@pytest.fixture
def force_bounded_window():
    from spark_rapids_tpu.exec import sort as S
    from spark_rapids_tpu.exec import window as W
    prev_rows = S._MERGE_OUT_ROWS
    S._MERGE_OUT_ROWS = 700
    yield W
    S._MERGE_OUT_ROWS = prev_rows


def _bounded_frame(p, f):
    return W_GO().rows_between(-p, f)


def test_bounded_window_aggs_multi_batch(force_bounded_window):
    Wm = force_bounded_window
    before = Wm.BOUNDED_WINDOW_EVENTS
    d = _big_data(5000)
    d["o"] = np.arange(len(d["o"]))    # unique order: frame-deterministic
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(d, num_partitions=4)
        .select(F.col("g"), F.col("o"), F.col("v"),
                F.Alias(F.sum("v").over(_bounded_frame(3, 2)), "bs"),
                F.Alias(F.count("v").over(_bounded_frame(3, 2)), "bc"),
                F.Alias(F.min("v").over(_bounded_frame(5, 0)), "bmin"),
                F.Alias(F.max("v").over(_bounded_frame(0, 4)), "bmax")),
        ignore_order=True, approx_float=True, conf=BOUNDED_CONF)
    assert Wm.BOUNDED_WINDOW_EVENTS > before, "bounded path did not engage"


def test_bounded_window_single_group_spans_batches(force_bounded_window):
    """One partition across every chunk: tails chain through the whole
    stream; frames straddling chunk boundaries must match the one-shot
    oracle exactly."""
    n = 3000
    rng = np.random.default_rng(5)
    d = {"g": np.ones(n, dtype=np.int64),
         "o": np.arange(n),
         "v": rng.integers(0, 100, n).astype(np.int64)}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(d, num_partitions=3)
        .select(F.col("o"),
                F.Alias(F.sum("v").over(_bounded_frame(7, 7)), "bs"),
                F.Alias(F.avg("v").over(_bounded_frame(2, 2)), "ba")),
        ignore_order=True, approx_float=True, conf=BOUNDED_CONF)


def test_bounded_window_lag_lead_multi_batch(force_bounded_window):
    """lag/lead ride the bounded tail-carry path (their offsets define
    the span)."""
    Wm = force_bounded_window
    before = Wm.BOUNDED_WINDOW_EVENTS
    n = 2500
    d = {"g": (np.arange(n) // 500).astype(np.int64),
         "o": np.arange(n),
         "v": np.arange(n, dtype=np.int64) * 3}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(d, num_partitions=3)
        .select(F.col("g"), F.col("o"),
                F.Alias(F.lag("v", 2).over(W_GO()), "lg"),
                F.Alias(F.lead("v", 3).over(W_GO()), "ld")),
        ignore_order=True, conf=BOUNDED_CONF)
    assert Wm.BOUNDED_WINDOW_EVENTS > before


def test_bounded_window_oom_injection(force_bounded_window):
    """The chunked path under deterministic OOM injection: retries must
    not corrupt the carried tail."""
    n = 2000
    rng = np.random.default_rng(11)
    d = {"g": (np.arange(n) % 5).astype(np.int64),
         "o": np.arange(n),
         "v": rng.integers(0, 50, n).astype(np.int64)}
    conf = dict(BOUNDED_CONF)
    conf["spark.rapids.sql.test.injectRetryOOM"] = "2"
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(d, num_partitions=2)
        .select(F.col("g"), F.col("o"),
                F.Alias(F.sum("v").over(_bounded_frame(4, 1)), "bs")),
        ignore_order=True, conf=conf)
