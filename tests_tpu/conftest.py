"""Real-accelerator differential test tier.

The reference runs its ScalaTest tier against real GPUs
(/root/reference/tests/README.md:8-21); this directory is the analog: the
platform is left UNforced so the engine runs on the actual TPU chip, while
the CPU oracle stays host-side numpy/arrow.  Run with:

    python -m pytest tests_tpu -q

The whole tier skips when no accelerator backend is present, so it is
safe to invoke unconditionally; `tests/` (forced-CPU, virtual 8-device
mesh) remains the breadth tier.

TPU float64 caveat (documented in docs/compatibility.md): XLA:TPU
emulates f64 as two f32s — ~49-bit precision, f32 exponent range.  Data
generators here keep doubles within +/-1e30 and comparisons use the
relative tolerance already built into tests/asserts.py.
"""

import os
import sys

# ensure `tests.asserts` resolves when running `pytest tests_tpu` alone
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    if jax.default_backend() in ("cpu",):
        skip = pytest.mark.skip(reason="no accelerator backend; the real-TPU "
                                       "tier needs a TPU device")
        for item in items:
            item.add_marker(skip)


@pytest.fixture
def rng():
    return np.random.default_rng(7)
