"""Differential core on the real TPU: expressions, sort, aggregate, join,
window, exchange — the subset whose device code paths differ from the
forced-CPU backend (64-bit bitcast rewrites, dd float64 emulation, x64
rewriter coverage).  Reference analog: the real-GPU ScalaTest tier
(SparkQueryCompareTestSuite.scala).  Everything routes through
session.sql so parser -> analyzer -> planner -> device execution is the
unit under test."""

import numpy as np

from tests.asserts import assert_tpu_and_cpu_are_equal_collect

RNG = np.random.default_rng(11)
N = 4000


def _data():
    # doubles stay within the dd-representable range (docs/compatibility.md)
    return {
        "a": RNG.integers(-1000, 1000, N).astype(np.int64),
        "b": RNG.integers(0, 50, N).astype(np.int32),
        "d": np.where(RNG.random(N) < 0.05, np.nan,
                      RNG.standard_normal(N) * 1e6),
        "f": RNG.standard_normal(N).astype(np.float32),
        "s": [None if i % 13 == 0 else f"k-{i % 37:02d}" for i in range(N)],
    }


_DATA = _data()

_DIM = {"b": np.arange(50, dtype=np.int32),
        "name": [f"n{i}" for i in range(50)]}


def _run_sql(query, views=None, n_parts=1, ignore_order=True, conf=None):
    views = views or {"t": _DATA}

    def fn(session):
        for name, data in views.items():
            session.create_or_replace_temp_view(
                name, session.create_dataframe(data,
                                               num_partitions=n_parts))
        return session.sql(query)

    full_conf = {"spark.rapids.sql.test.enabled": "false"}
    full_conf.update(conf or {})
    assert_tpu_and_cpu_are_equal_collect(
        fn, ignore_order=ignore_order, conf=full_conf)


def test_project_filter_arithmetic():
    _run_sql("select a, a * 3 as a3, d + cast(f as double) as df, s "
             "from t where a > 0")


def test_sort_double_key():
    # the round-2 showstopper: ORDER BY over a DOUBLE key on real TPU
    _run_sql("select a, d from t order by d", ignore_order=False)


def test_sort_double_desc_nulls():
    _run_sql("select a, d from t order by d desc, a", ignore_order=False)


def test_sort_string_and_int():
    _run_sql("select s, a from t order by s, a desc", ignore_order=False)


def test_groupby_int_key():
    _run_sql("select b, sum(a) as sa, min(d) as mn, max(d) as mx, "
             "count(a) as c from t group by b")


def test_groupby_string_double_avg():
    _run_sql("select s, avg(d) as ad, sum(cast(f as double)) as sf "
             "from t group by s")


def test_join_inner_int():
    _run_sql("select t.a, t.b, r.name from t join r on t.b = r.b",
             views={"t": _DATA, "r": _DIM})


def test_join_double_key():
    # join keys hashed through the dd word path on TPU
    keys = RNG.standard_normal(64) * 100
    left = {"k": np.repeat(keys, 4), "v": np.arange(256, dtype=np.int64)}
    right = {"k": keys, "w": np.arange(64, dtype=np.int64)}
    _run_sql("select l.k, l.v, r.w from l join r on l.k = r.k",
             views={"l": left, "r": right})


def test_window_running_sum():
    _run_sql("select b, a, sum(a) over (partition by b order by a, d "
             "rows between unbounded preceding and current row) as rs "
             "from t")


def test_shuffle_hash_partitioned_agg():
    _run_sql("select s, sum(a) as sa from t group by s", n_parts=4)


def test_range_partition_sort_double():
    # multi-partition global sort: sample -> range bounds -> exchange
    _run_sql("select a, d from t order by d", n_parts=4,
             ignore_order=False)


def test_hash_function_values():
    # Spark-compatible murmur3 over int+string: exact on device
    _run_sql("select hash(a, s) as h, a from t")


def test_sql_end_to_end():
    _run_sql("select b, count(*) as c, sum(a) as sa from t "
             "where a > -500 group by b order by b", ignore_order=False)


def test_out_of_core_sort_on_chip():
    """Round-4 external sort (device runs + packed-key merge) forced via
    the session conf, on the real chip."""
    _run_sql("select a, d from t order by d, a", ignore_order=False,
             conf={"spark.rapids.sql.test.sort.forceOutOfCore": "true"})


def test_agg_merge_repartition_on_chip():
    """Round-4 out-of-core aggregate merge (hash re-partition fallback)
    forced via conf, on the real chip."""
    _run_sql("select b, count(*) as c, sum(a) as sa, min(a) as mn "
             "from t group by b", n_parts=2,
             conf={"spark.rapids.sql.test.agg.forceMergeRepartitionDepth":
                   "1"})


def test_running_window_carry_on_chip():
    """Round-4 batched running windows: carry state across sort chunks on
    the real chip (running aggregates + rank family)."""
    _run_sql(
        "select b, a, row_number() over (partition by b order by a) rn,"
        " sum(a) over (partition by b order by a"
        "              rows between unbounded preceding and current row"
        "             ) rs from t where a <> 0", n_parts=2,
        conf={"spark.rapids.sql.test.window.forceRunning": "true",
              "spark.rapids.sql.test.sort.forceOutOfCore": "true"})


def test_count_distinct_on_chip():
    _run_sql("select b, count(distinct s) as cd from t group by b")


def test_bounded_window_tail_carry_on_chip():
    """Round-5 chunked bounded frames: (P+F)-row tail carried across sort
    chunks on the real chip (frames straddling chunk boundaries)."""
    _run_sql(
        "select b, a,"
        " sum(a) over (partition by b order by a"
        "              rows between 3 preceding and 2 following) bs,"
        " count(a) over (partition by b order by a"
        "                rows between 5 preceding and current row) bc"
        " from t where a <> 0", n_parts=2,
        conf={"spark.rapids.sql.test.window.forceBoundedBatched": "true",
              "spark.rapids.sql.test.sort.forceOutOfCore": "true"})


def test_speculative_join_sizing_on_chip():
    """Round-5 speculative pair-table sizing: an exploding join (every
    probe row matches many build rows) must overflow the probe-bucket
    guess and replay exactly, transparently."""
    dup = {"b": np.repeat(np.arange(10, dtype=np.int32), 40),
           "v": np.arange(400, dtype=np.int64)}
    _run_sql("select t.b, count(d.v) c from t join d on t.b = d.b "
             "group by t.b order by t.b",
             views={"t": _DATA, "d": dup})
